"""Design registry + packed-row dispatch of the evaluation service.

The engine owns the jax-facing half of serving: it resolves designs
into packed bucket rows (:func:`raft_tpu.api.pack_for_serving`) and
dispatches coalesced request groups through the SAME
``_cached_jit``/AOT-bank funnel the batch sweeps use
(:func:`raft_tpu.parallel.sweep._cached_jit` with the
``sweep_heterogeneous`` ``"bucket"`` memo key) — a program warmed by
``python -m raft_tpu.aot warmup --kinds serve`` (or by any
heterogeneous sweep at the same batch size) is THE program a serving
tick loads, so a warmed fresh server answers its first request with
zero backend compilations.

Batch sizes are a **ladder**: every dispatch pads its rows up to the
next ladder size with masked repeat rows (dropped on fan-out), so
arbitrary tick occupancies reuse a handful of compiled programs
instead of minting one per pending count.  Rung selection is
cost-driven by default (``RAFT_TPU_SERVE_LADDER=cost``): the pow2
candidates ``dp, 2*dp, ... <= RAFT_TPU_SERVE_MAX_BATCH`` are warmed,
then :func:`refine_ladder` prunes the rungs whose measured dispatch
wall is flat vs the next rung (fixed overhead floor / under-utilized
device: padding up is free there) and keeps the rungs where the wall
scales (padding costs real time: finer rungs win).  The candidate set
is exactly what the ``serve`` warmup kind warms, so a pruned ladder
only ever dispatches warmed programs.
"""

from __future__ import annotations

import os
import time

import numpy as np

from raft_tpu.obs import metrics
from raft_tpu.obs.spans import current_ids, span
from raft_tpu.structure import bucketing
from raft_tpu.utils import config
from raft_tpu.utils.structlog import log_event

#: the default dispatched out_keys — ``status`` is NON-OPTIONAL (the
#: per-request error semantics read it), :func:`normalize_out_keys`
#: enforces it
DEFAULT_OUT_KEYS = ("PSD", "X0", "status")


def normalize_out_keys(out_keys):
    """The dispatched out_keys tuple: caller order preserved,
    ``status`` appended when missing.  Both the batcher and the
    ``serve`` warmup kind normalize through here — the out_keys tuple
    is part of the program memo/bank key, so they must agree exactly."""
    keys = tuple(out_keys or DEFAULT_OUT_KEYS)
    return keys if "status" in keys else keys + ("status",)


class DesignEntry:
    """One registered design: the built model resolved into its bucket
    routing key, packed batch row and cache fingerprint."""

    __slots__ = ("name", "model", "sig", "packed", "fingerprint", "axes")

    def __init__(self, name, model):
        from raft_tpu.api import pack_for_serving

        self.name = name
        self.model = model
        self.sig, self.packed, self.fingerprint = pack_for_serving(model)
        # per-axis (real, padded) counts for the waste-attribution
        # metrics every serving dispatch feeds
        self.axes = bucketing.axis_counts(model, self.sig)

    def __repr__(self):
        return (f"DesignEntry({self.name!r}, "
                f"bucket={bucketing.signature_fingerprint(self.sig)})")


class Registry:
    """Named design registry + content-addressed inline-design cache.

    ``register`` builds the model once at startup (host build seconds,
    paid before the socket binds); inline per-request designs go
    through :meth:`resolve_inline`, which caches built entries by
    design-content fingerprint so a tenant re-posting the same YAML
    pays the build once.  The inline cache is LRU-BOUNDED
    (``max_inline``): a full Model + packed pytree is megabytes, and an
    optimizer tenant posting a slightly different design every iterate
    (the WEIS pattern) must recycle slots, not grow the always-on
    server's RSS without limit."""

    def __init__(self, max_inline=32):
        self._by_name: dict[str, DesignEntry] = {}
        self._max_inline = int(max_inline)
        self._inline: dict[str, DesignEntry] = {}  # fingerprint -> entry

    def register(self, name, design):
        """Build + pack one design (path or dict) under ``name``
        (named registrations are permanent — startup designs)."""
        entry = self._build(name, design)
        self._by_name[entry.name] = entry
        return entry

    def _build(self, name, design):
        import raft_tpu

        base_dir = (os.path.dirname(os.path.abspath(design))
                    if isinstance(design, str) else None)
        model = raft_tpu.Model(design, base_dir=base_dir)
        return DesignEntry(str(name), model)

    def get(self, name):
        return self._by_name.get(str(name))

    def resolve_inline(self, design_dict):
        """Entry for an inline design dict: built + LRU-cached by
        content fingerprint (repeat posts hit; the least-recently-used
        inline entry is dropped past ``max_inline``)."""
        from raft_tpu.aot.bank import content_fingerprint

        fp = content_fingerprint(design_dict)
        for named in self._by_name.values():   # inline post of a
            if named.fingerprint == fp:        # registered design
                return named
        entry = self._inline.get(fp)
        if entry is not None:
            self._inline.pop(fp)       # refresh recency (insert order)
            self._inline[fp] = entry
            return entry
        metrics.counter("serve_inline_designs").inc()
        entry = self._build(f"inline-{fp[:12]}", design_dict)
        while len(self._inline) >= self._max_inline:
            self._inline.pop(next(iter(self._inline)))
            metrics.counter("serve_inline_evictions").inc()
        self._inline[fp] = entry
        return entry

    def names(self):
        return sorted(self._by_name)

    def __len__(self):
        return len(self._by_name)


# --------------------------------------------------------------- dispatch


def batch_ladder(mesh, max_batch=None, policy=None):
    """The padded batch sizes the service dispatches (and the ``serve``
    warmup kind warms), per ``RAFT_TPU_SERVE_LADDER``:

    * ``pow2`` — ``dp, 2*dp, ...`` up to ``RAFT_TPU_SERVE_MAX_BATCH``
      (at least one rung): the legacy blind ladder;
    * ``cost`` (default) — the same pow2 CANDIDATES here; after warmup
      has measured every rung's dispatch wall through the cost ledger,
      :func:`refine_ladder` prunes the rungs whose wall is flat vs the
      next rung (dispatching padded bigger costs ~nothing there, so
      the extra program bought nothing but warmup/bank bill);
    * an explicit ascending comma list (e.g. ``1,4,16,64``) — rungs
      used verbatim (each must divide by the mesh's dp axis).
    """
    dp = mesh.shape.get("dp", 1)
    if max_batch is None:
        max_batch = int(config.get("SERVE_MAX_BATCH"))
    if policy is None:
        policy = str(config.get("SERVE_LADDER") or "cost").strip().lower()
    if policy in ("pow2", "cost"):
        sizes = [dp]
        while sizes[-1] * 2 <= max(max_batch, dp):
            sizes.append(sizes[-1] * 2)
        return tuple(sizes)
    try:
        sizes = tuple(int(s) for s in policy.split(",") if s.strip())
    except ValueError:
        raise ValueError(
            f"RAFT_TPU_SERVE_LADDER={policy!r}: expected 'pow2', 'cost' "
            "or an ascending comma list of rung sizes")
    if not sizes or any(b <= a for a, b in zip(sizes, sizes[1:])) or \
            any(s < dp or s % dp for s in sizes):
        raise ValueError(
            f"RAFT_TPU_SERVE_LADDER={policy!r}: rungs must be strictly "
            f"ascending multiples of the dp axis size ({dp})")
    return sizes


def prune_ladder(sizes, walls, tol=None):
    """Cost-driven rung selection: keep a rung only where it measurably
    saves dispatch wall over the next kept rung.

    ``walls`` maps rung -> measured mean seconds per dispatch (missing
    rungs are kept — never prune on ignorance).  Walking from the top
    rung (always kept: it is the tick's chunk cap) downward, rung ``r``
    survives only if ``wall(next_kept) > tol * wall(r)`` — i.e. padding
    ``r``'s occupancy up to the next kept rung would cost real time
    (padding dominates there: finer rungs).  Where the wall is flat
    (fixed dispatch overhead floor, under-utilized device) the rung is
    dropped: fewer programs to warm/bank, identical latency."""
    if tol is None:
        tol = float(config.get("SERVE_LADDER_TOL"))
    sizes = sorted(sizes)
    keep = [sizes[-1]]
    for r in reversed(sizes[:-1]):
        w_r, w_next = walls.get(r), walls.get(keep[-1])
        if w_r is None or w_next is None or w_next > tol * w_r:
            keep.append(r)
    return tuple(sorted(keep))


def ladder_walls(entries, sizes, mesh=None, out_keys=DEFAULT_OUT_KEYS):
    """Measured dispatch wall per ladder rung, from the in-process
    cost ledger (:data:`raft_tpu.aot.bank.PROGRAM_STATS` — populated by
    the warmup dispatches / prior serving load of a bank-routed
    process).  Per program the BEST observed wall (``wall_min_s``) is
    preferred over the mean — one scheduler hiccup during a warmup
    dispatch must not mis-shape the ladder for the server's lifetime,
    which is also why :func:`warm` dispatches every rung twice.  Each
    rung then reports the WORST of that across the served bucket
    signatures, so a rung is only ever pruned when it is flat for
    every tenant.  Rungs nothing has measured map to None."""
    from raft_tpu.aot import bank
    from raft_tpu.parallel.sweep import make_mesh

    if mesh is None:
        mesh = make_mesh()
    out_keys = normalize_out_keys(out_keys)
    by_sig = {}
    for e in entries:
        by_sig.setdefault(e.sig, e)
    walls = {}
    for rung in sizes:
        worst = None
        for e in by_sig.values():
            try:
                key, _ = program_identity(e, mesh=mesh, out_keys=out_keys,
                                          rows=rung)
            except Exception:  # noqa: BLE001 — ladder tuning is telemetry
                continue
            st = bank.program_stats(key)
            if st.get("dispatches") and st.get("wall_s", 0) > 0:
                w = st.get("wall_min_s") or (st["wall_s"]
                                             / st["dispatches"])
                worst = w if worst is None else max(worst, w)
        walls[rung] = worst
    return walls


def refine_ladder(entries, sizes, mesh=None, out_keys=DEFAULT_OUT_KEYS):
    """Post-warmup ladder refinement (``RAFT_TPU_SERVE_LADDER=cost``):
    prune the warmed candidate rungs whose measured dispatch wall is
    flat vs the next rung.  Under any other policy — or with no
    measurements (e.g. ``RAFT_TPU_AOT=off``, where dispatches are not
    cost-ledgered) — the candidates come back unchanged.  Every
    returned rung was warmed (pruning only ever drops rungs), so the
    steady-state zero-recompile contract is untouched."""
    policy = str(config.get("SERVE_LADDER") or "cost").strip().lower()
    if policy != "cost" or len(sizes) <= 1:
        return tuple(sizes)
    walls = ladder_walls(entries, sizes, mesh=mesh, out_keys=out_keys)
    pruned = prune_ladder(sizes, walls)
    if tuple(pruned) != tuple(sizes):
        log_event("serve_ladder", candidates=list(sizes),
                  sizes=list(pruned),
                  walls_ms={str(r): (round(w * 1e3, 3) if w else None)
                            for r, w in walls.items()})
    return pruned


def pick_padded(n, sizes):
    """Smallest ladder size holding ``n`` rows (callers chunk to
    ``sizes[-1]`` first)."""
    for s in sizes:
        if s >= n:
            return s
    return sizes[-1]


def _pad1(a, rows):
    a = np.asarray(a, dtype=float)
    if len(a) == rows:
        return a
    return np.concatenate([a, np.full(rows - len(a), a[-1])])


def flags_extra():
    """The trace-time state that shapes served numbers beyond the
    design + case — folded into every result-cache key so a flag flip
    (dtype policy, escalation iteration scale) never serves stale
    rows."""
    import jax

    from raft_tpu.parallel.sweep import _flags_key

    return _flags_key() + (bool(jax.config.jax_enable_x64),)


def dispatch(entries, Hs, Tp, beta, out_keys=DEFAULT_OUT_KEYS, mesh=None,
             padded=None, record_metrics=True, timings=None):
    """Evaluate one coalesced request group (ONE bucket signature).

    entries : per-row :class:`DesignEntry` (repeat an entry to evaluate
        it under several sea states)
    Hs/Tp/beta : per-row scalars, aligned with ``entries``
    padded : the program batch size (a :func:`batch_ladder` rung);
        default: the smallest rung holding the rows
    record_metrics : False for non-serving traffic (startup warmup) so
        the occupancy/dispatch metrics describe ONLY real request load
    timings : optional dict the call fills with ``solve_s`` (the
        batcher's tail-attribution stage split; it measures the full
        dispatch window itself) — an out-param so concurrent dispatch
        paths cannot misattribute each other's walls, which a
        module-global "last timings" would

    Returns ``{out_key: host numpy array}`` of length ``len(entries)``
    (padding rows dropped).  The memo/bank key is IDENTICAL to
    :func:`raft_tpu.parallel.sweep.sweep_heterogeneous`'s per-bucket
    key, so serving, sweeps and warmup all share programs.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raft_tpu.parallel.sweep import (_cached_jit, _flags_key, _mesh_key,
                                         make_mesh)
    from raft_tpu.utils.devices import enable_compile_cache

    enable_compile_cache()
    if mesh is None:
        mesh = make_mesh()
    n = len(entries)
    if n == 0:
        raise ValueError("empty dispatch group")
    sig = entries[0].sig
    if any(e.sig != sig for e in entries):
        raise ValueError("dispatch group mixes bucket signatures — the "
                         "batcher groups by signature before dispatching")
    if padded is None:
        padded = pick_padded(n, batch_ladder(mesh))
    if padded < n or padded % mesh.shape.get("dp", 1):
        raise ValueError(f"padded batch {padded} cannot hold {n} rows on "
                         f"mesh {dict(mesh.shape)}")

    ev = bucketing.get_bucket_evaluator(sig)
    case = dict(
        design=bucketing.stack_packed([e.packed for e in entries], padded),
        Hs=_pad1(Hs, padded), Tp=_pad1(Tp, padded), beta=_pad1(beta, padded))
    sharding = NamedSharding(mesh, P("dp"))
    in_sh = jax.tree_util.tree_map(lambda _: sharding, case)

    def build(ev=ev, in_sh=in_sh, keys=tuple(out_keys)):
        def one(c):
            with jax.named_scope("sweep_bucket"):
                return {kk: ev(c)[kk] for kk in keys}

        return jax.jit(jax.vmap(one), in_shardings=(in_sh,))

    fn = _cached_jit(ev, ("bucket", tuple(out_keys), sig, _mesh_key(mesh),
                          _flags_key()), build)
    # host-numpy device_put: no resharding program, no compile event
    # (see sweep_cases)
    args = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(np.asarray(x), s), case, in_sh)
    with span("sweep_dispatch", kind="serve", rows=n,
              bucket=bucketing.signature_fingerprint(sig)):
        t_solve0 = time.perf_counter()
        res = fn(args)
        res = {kk: np.asarray(res[kk])[:n] for kk in out_keys}
    # tail attribution: the batcher splits each coalesced request's
    # latency into stage walls; solve = compiled-program execution +
    # result fetch, the rest of the dispatch wall is pack/device_put
    if timings is not None:
        timings["solve_s"] = time.perf_counter() - t_solve0
    if record_metrics:
        metrics.counter("serve_dispatches").inc()
        metrics.counter("serve_rows_dispatched").inc(n)
        # batch-shape exemplar: WHICH compiled bucket produced the
        # biggest (or emptiest) dispatch, joinable to its span tree
        ex = {"sig": bucketing.signature_fingerprint(sig),
              "rows": int(n), "padded": int(padded)}
        ids = current_ids()
        if ids is not None:
            ex["trace_id"], ex["span_id"] = ids
        metrics.histogram("serve_batch_rows").observe(n, exemplar=ex)
        metrics.histogram("serve_batch_occupancy").observe(n / padded,
                                                           exemplar=ex)
        # waste attribution: the same per-axis pad accounting the
        # bucketed sweeps feed, here weighted by served request rows
        bucketing.observe_axis_waste([e.axes for e in entries],
                                     rows_valid=n, rows_padded=padded)
    return res


def escalate_row(entry, Hs, Tp, beta, out_keys=DEFAULT_OUT_KEYS, mesh=None):
    """Quarantine-style f64 re-solve of ONE request (per-request
    opt-in): re-dispatch the row solo under the escalation ladder's
    ``f64_cpu`` rung flags (float64 compute policy on a CPU mesh,
    relaxed compile budget — :func:`raft_tpu.parallel.resilience.
    _rung_flags`).  Returns ``(row, status_after)``; adoption policy is
    the caller's (the batcher only adopts a HEALTHY re-solve, like the
    sweep quarantine)."""
    from raft_tpu.parallel import resilience
    from raft_tpu.parallel.sweep import make_mesh

    if mesh is None:
        mesh = make_mesh()
    metrics.counter("serve_escalations").inc()
    with resilience._rung_flags("f64_cpu"):
        emesh = resilience._rung_mesh("f64_cpu", mesh)
        out = dispatch([entry], [Hs], [Tp], [beta], out_keys, mesh=emesh,
                       padded=emesh.shape.get("dp", 1))
    row = {kk: out[kk][0] for kk in out_keys}
    return row, int(np.asarray(row["status"]))


# ------------------------------------------------------------- provenance


def program_identity(entry, mesh=None, out_keys=DEFAULT_OUT_KEYS, rows=None):
    """The AOT-bank identity of the program that serves ``entry``:
    ``(entry_key, sidecar_meta | None)`` for the (bucket signature x
    smallest ladder rung) dispatch — the EXACT key
    :class:`~raft_tpu.aot.bank.BankedProgram` computes at dispatch
    time (same memo key, same device-put argument avals), derived
    without dispatching anything.  Startup-only cost: one device_put
    of a single packed design row."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raft_tpu.aot import bank
    from raft_tpu.parallel.sweep import _flags_key, _mesh_key, make_mesh

    if mesh is None:
        mesh = make_mesh()
    out_keys = normalize_out_keys(out_keys)
    rows = int(rows) if rows else batch_ladder(mesh)[0]
    case = dict(design=bucketing.stack_packed([entry.packed], rows),
                Hs=_pad1(np.full(1, 4.0), rows),
                Tp=_pad1(np.full(1, 9.0), rows),
                beta=_pad1(np.zeros(1), rows))
    sharding = NamedSharding(mesh, P("dp"))
    in_sh = jax.tree_util.tree_map(lambda _: sharding, case)
    args = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(np.asarray(x), s), case, in_sh)
    # the full memo key _cached_jit hands the bank: the dispatch tuple
    # plus the bucket evaluator's program-identity stamp (the signature
    # IS the program — structure.bucketing.make_bucket_evaluator)
    pk = ("bucket_evaluator", bank.content_fingerprint(list(entry.sig)))
    memo = ("bucket", tuple(out_keys), entry.sig, _mesh_key(mesh),
            _flags_key()) + (("program", pk),)
    key, _meta = bank.entry_key("bucket", memo, (args,))
    return key, bank.peek("bucket", memo, (args,))


def flags_fingerprint():
    """Short content hash of the trace-time flag state
    (:func:`flags_extra`) — the ``flags`` component of the provenance
    stamp: two replicas under divergent dtype/solver/x64 flags carry
    different fingerprints even when both are individually healthy."""
    import hashlib

    return hashlib.sha256(repr(flags_extra()).encode()).hexdigest()[:12]


def build_provenance(registry, mesh=None, out_keys=DEFAULT_OUT_KEYS,
                     sizes=None, replica_id=None):
    """Per-design provenance stamps for the ``x-raft-provenance``
    response header: ``{design_name: {bank_key, bank_sha, code, flags,
    replica}}`` plus a ``"*"`` base entry (code/flags/replica only)
    for inline designs.  Computed ONCE at startup — per request the
    stamp is a dict lookup and one precomputed header string, nothing
    more (the zero-overhead contract).

    The deterministic ``provenance_skew`` fault kind
    (:mod:`raft_tpu.utils.faults`, site ``serve_provenance``) perturbs
    the reported bank/code identity — the drill's stand-in for a
    genuinely stale-banked or env-skewed replica, detected by the
    router canary's cross-replica consistency check."""
    from raft_tpu.aot import bank
    from raft_tpu.utils import faults

    code = bank.code_fingerprint()
    flags = flags_fingerprint()
    rid = str(replica_id or f"pid-{os.getpid()}")
    skewed = faults.take("provenance_skew", "serve_provenance")
    base = {"code": code, "flags": flags, "replica": rid}
    try:
        from raft_tpu.aot import release as _release

        rel = _release.current_release()
    except Exception:  # noqa: BLE001 — provenance is telemetry
        rel = None
    if rel:
        # the release id resolved through the current pointer at warmup
        # — the version-aware canary groups replicas by this stamp
        base["release"] = rel
    out = {"*": dict(base)}
    for name in registry.names():
        entry = registry.get(name)
        try:
            key, side = program_identity(
                entry, mesh=mesh, out_keys=out_keys,
                rows=(sizes[0] if sizes else None))
        except Exception:  # noqa: BLE001 — provenance is telemetry
            key, side = None, None
        d = dict(base)
        d["bank_key"] = key or "none"
        d["bank_sha"] = ((side or {}).get("payload_sha256") or "none")[:16]
        if skewed:
            d["bank_sha"] = ("skew" + d["bank_sha"])[:16]
            d["bank_key"] = "skew-" + d["bank_key"]
        out[name] = d
    return out


# ----------------------------------------------------------------- warmup


def warm(entries, mesh=None, out_keys=DEFAULT_OUT_KEYS, sizes=None):
    """Warm every program the service will dispatch for ``entries``:
    one dispatch per (bucket signature x ladder size) with synthetic
    sea states, through the production funnel — under
    ``RAFT_TPU_AOT=load`` each program is bank-loaded or
    compiled+exported; under ``require`` a cold bank fails HERE, before
    any client is waiting.  Returns per-program report dicts."""
    import jax

    from raft_tpu.parallel.sweep import make_mesh

    if mesh is None:
        mesh = make_mesh()
    if sizes is None:
        sizes = batch_ladder(mesh)
    out_keys = normalize_out_keys(out_keys)
    by_sig: dict = {}
    for e in entries:
        by_sig.setdefault(e.sig, []).append(e)
    reports = []
    rng = np.random.default_rng(0)
    for sig, group in by_sig.items():
        for rows in sizes:
            row_entries = [group[i % len(group)] for i in range(rows)]
            c0 = {k: metrics.counter(k).value for k in
                  ("aot_programs_loaded", "aot_programs_compiled")}
            t0 = time.perf_counter()
            out = dispatch(row_entries, rng.uniform(2.0, 8.0, rows),
                           rng.uniform(6.0, 14.0, rows),
                           rng.uniform(-0.5, 0.5, rows),
                           out_keys=out_keys, mesh=mesh, padded=rows,
                           record_metrics=False)
            jax.block_until_ready(out)
            # a second, execution-only dispatch: the cost-ladder tuner
            # reads the BEST wall per rung, and one sample (possibly
            # fattened by post-load lazy init or a scheduler pause)
            # must not shape the serving ladder
            jax.block_until_ready(
                dispatch(row_entries, rng.uniform(2.0, 8.0, rows),
                         rng.uniform(6.0, 14.0, rows),
                         rng.uniform(-0.5, 0.5, rows),
                         out_keys=out_keys, mesh=mesh, padded=rows,
                         record_metrics=False))
            rep = dict(
                kind="serve", rows=rows,
                bucket=bucketing.signature_fingerprint(sig),
                wall_s=round(time.perf_counter() - t0, 2),
                loaded=metrics.counter("aot_programs_loaded").value
                - c0["aot_programs_loaded"],
                compiled=metrics.counter("aot_programs_compiled").value
                - c0["aot_programs_compiled"])
            log_event("aot_warmup", kind="serve", n=rows,
                      loaded=rep["loaded"], compiled=rep["compiled"],
                      wall_s=rep["wall_s"])
            reports.append(rep)
    return reports
