"""Seeded protocol bug: the pre-PR-13 claim-collision live-twin.

Before the lease primitives were factored onto ``O_CREAT|O_EXCL``,
claiming was a check-then-write: stat the lease path, and when absent
write the record.  Two workers racing the same shard id could both see
"absent" and both write — two live claimants of one lease (the
live-twin), with the loser's record silently clobbered.

The model checker must catch this through the single-holder invariant:
a plain (non-exclusive, non-atomic-replace) write to a lease path is a
hijack channel regardless of interleaving.  ``python -m
raft_tpu.analysis protocol check --fixture <this file>`` must exit 1.
"""

import json

from raft_tpu.utils import fsops


def lease_claim(path, rec):
    # the historical TOCTOU: exists-check then plain write
    if fsops.exists(path):
        return False
    fsops.write_text(path, json.dumps(rec))
    return True


# fleet.py imports the primitive BY VALUE, so both bindings need the
# buggy implementation for the revert to be faithful.
PATCHES = {
    "raft_tpu.parallel.fabric:lease_claim": lease_claim,
    "raft_tpu.serve.fleet:lease_claim": lease_claim,
}

# the live-twin lives in the sweep ledger's claim path
SCENARIOS = ("lease-ledger",)
