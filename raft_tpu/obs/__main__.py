"""CLI for the telemetry subsystem (pure stdlib, no jax).

    python -m raft_tpu.obs report <run.jsonl>
    python -m raft_tpu.obs report --merge <capture-dir | shard.jsonl ...>
    python -m raft_tpu.obs trace  <run.jsonl> -o trace.json
    python -m raft_tpu.obs trace  --merge <capture-dir | shards...> -o t.json
    python -m raft_tpu.obs events
    python -m raft_tpu.obs spans

``report`` prints the per-stage wall-time tree, counter table, program
cost ledger and reliability summary of one ``RAFT_TPU_LOG`` capture;
``trace`` exports it as Chrome/Perfetto trace-event JSON (load in
``chrome://tracing`` or https://ui.perfetto.dev).  ``--merge`` accepts
several per-process capture shards (or a directory of
``trace-<pid>.jsonl`` files, the ``RAFT_TPU_LOG=<dir>`` layout) and
assembles coordinator + workers + server onto ONE wall-clock timeline
using the per-process ``proc_start`` clock anchors; ``--check`` (trace)
additionally exits 1 when the merged capture has unmatched span begins
or orphan spans (a parent id resolving to no span) — the cross-process
propagation acceptance gate.  ``events``/``spans`` list the registered
schemas.  Exit codes: 0 ok, 1 check failed, 2 usage/input error.
"""

from __future__ import annotations

import argparse
import json
import sys


def _load(paths, merge):
    from raft_tpu.obs import report

    try:
        if merge:
            events, bad, info = report.merge_captures(paths)
        else:
            if len(paths) != 1:
                print("multiple captures need --merge", file=sys.stderr)
                raise SystemExit(2)
            events, bad = report.read_events(paths[0])
            info = None
    except OSError as e:
        print(f"cannot read {getattr(e, 'filename', None) or paths}: {e}",
              file=sys.stderr)
        raise SystemExit(2)
    if not events:
        print(f"{', '.join(paths)}: no parseable events (was RAFT_TPU_LOG "
              "pointed here during the run?)", file=sys.stderr)
        raise SystemExit(2)
    return events, bad, info


def _cmd_report(args):
    from raft_tpu.obs import report

    events, bad, _ = _load(args.jsonl, args.merge)
    sys.stdout.write(report.render_report(
        events, bad, source=", ".join(args.jsonl)))
    return 0


def _cmd_trace(args):
    from raft_tpu.obs import report

    events, bad, info = _load(args.jsonl, args.merge)
    trace = report.chrome_trace(events, merged=args.merge)
    with open(args.output, "w") as f:
        json.dump(trace, f)
    meta = trace["otherData"]
    print(f"{args.output}: {len(trace['traceEvents'])} trace events "
          f"({meta['spans_matched']} spans across {meta['pids']} "
          f"process(es), {meta['traces']} trace id(s)"
          + (f", {meta['spans_unmatched']} unmatched" if
             meta["spans_unmatched"] else "")
          + (f", {meta['spans_orphaned']} orphaned" if
             meta["spans_orphaned"] else "")
          + (f"; {info['unanchored_files']} unanchored shard(s)"
             if info and info.get("unanchored_files") else "")
          + (f"; {bad} unparseable lines skipped" if bad else "")
          + ") — open in chrome://tracing or ui.perfetto.dev")
    if args.check and (meta["spans_unmatched"] or meta["spans_orphaned"]):
        print(f"check FAILED: {meta['spans_unmatched']} unmatched begin(s), "
              f"{meta['spans_orphaned']} orphan span(s) — cross-process "
              "propagation is broken somewhere", file=sys.stderr)
        return 1
    return 0


def _cmd_events(_args):
    from raft_tpu.obs import events as ev

    for name, fields, help_ in ev.describe():
        print(f"{name:32s} {', '.join(fields):56s} {help_}")
    return 0


def _cmd_spans(_args):
    from raft_tpu.obs import events as ev

    for name, help_ in ev.describe_spans():
        print(f"{name:32s} {help_}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m raft_tpu.obs")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("report", help="summarize one RAFT_TPU_LOG capture")
    p.add_argument("jsonl", nargs="+",
                   help="captured JSONL stream(s), or a capture directory "
                        "with --merge")
    p.add_argument("--merge", action="store_true",
                   help="assemble several per-process shards onto one "
                        "wall-clock timeline (proc_start anchors)")

    p = sub.add_parser("trace",
                       help="export a capture as Chrome trace events")
    p.add_argument("jsonl", nargs="+",
                   help="captured JSONL stream(s), or a capture directory "
                        "with --merge")
    p.add_argument("-o", "--output", default="trace.json",
                   help="output path (default trace.json)")
    p.add_argument("--merge", action="store_true",
                   help="assemble several per-process shards onto one "
                        "wall-clock timeline (proc_start anchors)")
    p.add_argument("--check", action="store_true",
                   help="exit 1 on unmatched or orphan spans (CI gate "
                        "for cross-process trace propagation)")

    sub.add_parser("events", help="list the registered event schema")
    sub.add_parser("spans", help="list the registered span names")

    args = ap.parse_args(argv)
    return {"report": _cmd_report, "trace": _cmd_trace,
            "events": _cmd_events, "spans": _cmd_spans}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
