"""Telemetry subsystem: spans, metrics, heartbeats, trace tooling.

SURVEY §5.1/§5.5: the reference's only instrumentation is a wall-clock
print around the QTF loop.  The PR-1..4 runtime (retries, quarantine,
escalation, recompile sentinel) emits flat JSONL events; this package
turns that stream into first-class telemetry:

* :mod:`raft_tpu.obs.spans` — hierarchical, contextvar-propagated
  spans (``trace_id``/``span_id``/``parent_id``) around the drivers,
  statics/dynamics solves, sweep shards, retry attempts and escalation
  rungs, with ``jax.profiler.TraceAnnotation`` mirrors under
  ``RAFT_TPU_PROFILE`` so host spans line up with device traces;
* :mod:`raft_tpu.obs.metrics` — a process-wide thread-safe registry
  (counters/gauges/log-bucket histograms) fed by the existing event
  sites, snapshotted into the sweep manifest + ``metrics.json`` and
  exportable as Prometheus text (``RAFT_TPU_METRICS``);
* :mod:`raft_tpu.obs.heartbeat` — an optional device sampler thread
  (``RAFT_TPU_HEARTBEAT_S``) for OOM forensics;
* :mod:`raft_tpu.obs.events` — the lint-enforced registry of every
  event name (``event-name`` rule);
* :mod:`raft_tpu.obs.report` — ``python -m raft_tpu.obs report`` and
  ``... trace`` (Chrome/Perfetto export) over captured JSONL;
* :mod:`raft_tpu.obs.alerts` — the ACTIVE layer: declarative alert
  rules over the registry (``RAFT_TPU_ALERT_EVAL_S`` daemon,
  ``alert_fire``/``alert_resolve``, the ``RAFT_TPU_ALERTS`` sink,
  ``GET /alerts``, ``python -m raft_tpu.obs alerts``) plus the
  ``x-raft-provenance`` codec the serving canary cross-checks.

All instrumentation is host-side only: nothing here runs under a jax
trace, the jaxpr primitive baseline is unchanged, and with
``RAFT_TPU_LOG`` unset a span costs a few microseconds (sink check +
clock read + histogram observe).  This module
imports no jax (the report/trace/events CLIs and the linter load it
backend-free); jax access inside heartbeat/spans is lazy and gated.
"""

from raft_tpu.obs import events, metrics  # noqa: F401
from raft_tpu.obs.heartbeat import Heartbeat, maybe_heartbeat  # noqa: F401
from raft_tpu.obs.spans import (current_ids, format_traceparent,  # noqa: F401
                                parse_traceparent, propagation_env, span)
from raft_tpu.utils.structlog import run_id  # noqa: F401
