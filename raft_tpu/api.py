"""High-level traced evaluation API: one design evaluation as a pure
jax function, ready to jit / vmap / shard_map.

The reference evaluates one (design, load case) pair by a long chain of
Python method calls mutating FOWT state (Model.analyzeCases,
raft_model.py:264-433).  Here the same chain — static equilibrium →
wave excitation → iterative drag linearisation → impedance solve →
response statistics — is closed over the build-time structure and
exposed as ``evaluate(Hs, Tp, beta)``:

* jit once, then every additional (case x design-parameter) evaluation
  is a batched tensor program;
* ``vmap`` adds case/sea-state axes;
* device-mesh sharding (see :mod:`raft_tpu.parallel.sweep`) scales the
  batch across a TPU pod with XLA inserting the collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.models.dynamics import solve_dynamics_fowt, system_response
from raft_tpu.models.statics_solve import solve_equilibrium
from raft_tpu.physics import morison
from raft_tpu.physics.mooring import mooring_stiffness
from raft_tpu.physics.statics import calc_statics, node_T, platform_kinematics
from raft_tpu.ops import waves as wv


def make_design_evaluator(model):
    """Build ``evaluate(params) -> outputs`` with traced *design*
    parameters — the 10k-design-sweep axis of the north star.

    params (all optional, broadcastable scalars):
      Hs, Tp, beta       sea state
      Cd_scale, Ca_scale strip drag / added-mass coefficient multipliers
      L_moor_scale       mooring unstretched-length multiplier

    Geometry shapes are fixed per design family; the parameters scale
    the build-time tensors inside the trace, so the whole map is
    jit/vmap-able over designs AND differentiable (e.g. optimize
    mooring length against a response metric with ``jax.grad``).
    """
    import dataclasses

    fs = model.fowtList[0]
    ms0 = model.ms
    fh = model.hydro[0]
    ss0 = fh.strips
    w = jnp.asarray(model.w)
    k = jnp.asarray(model.k)
    dw = model.w[1] - model.w[0]
    nw = model.nw
    nDOF = fs.nDOF

    stat = model.statics()
    K_h = np.asarray(stat["C_struc"] + stat["C_hydro"])
    F_und = np.asarray(stat["W_struc"] + stat["W_hydro"] + stat["f0_additional"])
    M_struc = np.asarray(stat["M_struc"])

    def evaluate(params):
        Hs = params.get("Hs", 6.0)
        Tp = params.get("Tp", 12.0)
        beta = params.get("beta", 0.0)
        Cd_s = params.get("Cd_scale", 1.0)
        Ca_s = params.get("Ca_scale", 1.0)
        L_s = params.get("L_moor_scale", 1.0)

        ss = dataclasses.replace(
            ss0,
            Cd_q=jnp.asarray(ss0.Cd_q) * Cd_s,
            Cd_p1=jnp.asarray(ss0.Cd_p1) * Cd_s,
            Cd_p2=jnp.asarray(ss0.Cd_p2) * Cd_s,
            Cd_End=jnp.asarray(ss0.Cd_End) * Cd_s,
            Ca_q=jnp.asarray(ss0.Ca_q) * Ca_s,
            Ca_p1=jnp.asarray(ss0.Ca_p1) * Ca_s,
            Ca_p2=jnp.asarray(ss0.Ca_p2) * Ca_s,
            Ca_End=jnp.asarray(ss0.Ca_End) * Ca_s,
            Cm_p1_w=1.0 + Ca_s * (jnp.asarray(ss0.Cm_p1_w) - 1.0),
            Cm_p2_w=1.0 + Ca_s * (jnp.asarray(ss0.Cm_p2_w) - 1.0),
        )
        ms = None
        if ms0 is not None:
            ms = dataclasses.replace(ms0, L=jnp.asarray(ms0.L) * L_s)

        # mean offsets
        X0, _ = solve_equilibrium(fs, ms, K_h, F_und, jnp.zeros(nDOF))

        r_nodes, R_ptfm, r_root = platform_kinematics(fs, X0)
        Tn = node_T(r_nodes, r_root)
        # hydro constants recomputed in-trace (coefficients are traced)
        hc = morison.hydro_constants(fs, ss, R_ptfm, r_nodes, Tn)

        S = wv.jonswap(w, Hs, Tp)
        zeta = jnp.sqrt(2.0 * S * dw).astype(complex)
        exc = morison.hydro_excitation(
            fs, ss, hc, zeta[None, :], jnp.asarray([beta]), w, k, Tn, r_nodes)

        C_moor = jnp.zeros((nDOF, nDOF))
        if ms is not None:
            C_moor = C_moor.at[:6, :6].add(mooring_stiffness(ms, X0[:6]))
        M_lin = jnp.broadcast_to(
            (jnp.asarray(M_struc) + hc["A_hydro"])[:, :, None], (nDOF, nDOF, nw))
        B_lin = jnp.zeros((nDOF, nDOF, nw))
        C_lin = jnp.asarray(K_h) + C_moor
        F_lin = exc["F_hydro_iner"][0]

        Z, _, Bmat = solve_dynamics_fowt(
            fs, ss, hc, exc["u"][0], M_lin, B_lin, C_lin, F_lin,
            w, Tn, r_nodes, n_iter=model.nIter, Xi_start=model.XiStart)
        F_wave = exc["F_hydro_iner"][0] + morison.drag_excitation(
            fs, ss, hc, Bmat, exc["u"][0], Tn, r_nodes)
        Xi = system_response(Z, F_wave[None])[0]
        return dict(
            X0=X0, Xi=Xi, RAO=wv.get_rao(Xi, zeta),
            PSD=0.5 * jnp.abs(Xi) ** 2 / dw, S=S,
        )

    return evaluate


def make_case_evaluator(model, n_stat_iter=12):
    """Build ``evaluate(Hs, Tp, beta) -> outputs`` for one design.

    All build-time structure (strips, topology, statics matrices) is
    resolved here; the returned function is pure jax on scalar sea-state
    inputs and fully differentiable.
    """
    fs = model.fowtList[0]
    ms = model.ms
    fh = model.hydro[0]
    ss = fh.strips
    w = jnp.asarray(model.w)
    k = jnp.asarray(model.k)
    dw = model.w[1] - model.w[0]
    nw = model.nw
    nDOF = fs.nDOF

    # closures stay host-side numpy: they lower to jit constants without
    # any device pull (the axon TPU tunnel only implements f32 d2h)
    stat = model.statics()
    K_h = np.asarray(stat["C_struc"] + stat["C_hydro"])
    F_und = np.asarray(stat["W_struc"] + stat["W_hydro"] + stat["f0_additional"])
    M_struc = np.asarray(stat["M_struc"])
    A_hydro = np.asarray(fh.hc0["A_hydro"])
    hc0 = fh.hc0

    def evaluate(Hs, Tp, beta):
        # --- mean offsets under zero mean environmental load
        X0, _ = solve_equilibrium(fs, ms, K_h, F_und, jnp.zeros(nDOF))

        # --- pose-dependent geometry
        r_nodes, R_ptfm, r_root = platform_kinematics(fs, X0)
        Tn = node_T(r_nodes, r_root)
        r, q, p1, p2 = morison.strip_frames(ss, R_ptfm, r_nodes)
        sub = r[:, 2] < 0
        hc = dict(hc0, r=r, q=q, p1=p1, p2=p2, sub=sub,
                  active=sub & jnp.asarray(ss.active))

        # --- sea state + excitation
        S = wv.jonswap(w, Hs, Tp)
        zeta = jnp.sqrt(2.0 * S * dw).astype(complex)
        exc = morison.hydro_excitation(
            fs, ss, hc, zeta[None, :], jnp.asarray([beta]), w, k, Tn, r_nodes
        )

        # --- linear system + iterative drag linearisation
        C_moor = jnp.zeros((nDOF, nDOF))
        if ms is not None:
            C_moor = C_moor.at[:6, :6].add(mooring_stiffness(ms, X0[:6]))
        M_lin = jnp.broadcast_to((M_struc + A_hydro)[:, :, None], (nDOF, nDOF, nw))
        B_lin = jnp.zeros((nDOF, nDOF, nw))
        C_lin = K_h + C_moor
        F_lin = exc["F_hydro_iner"][0]

        Z, Xi1, Bmat = solve_dynamics_fowt(
            fs, ss, hc, exc["u"][0], M_lin, B_lin, C_lin, F_lin,
            w, Tn, r_nodes, n_iter=model.nIter, Xi_start=model.XiStart,
        )
        F_wave = F_lin * 0 + exc["F_hydro_iner"][0] + morison.drag_excitation(
            fs, ss, hc, Bmat, exc["u"][0], Tn, r_nodes
        )
        Xi = system_response(Z, F_wave[None])[0]  # (nDOF, nw)

        RAO = wv.get_rao(Xi, zeta)
        PSD = 0.5 * jnp.abs(Xi) ** 2 / dw
        return dict(X0=X0, Xi=Xi, RAO=RAO, PSD=PSD, S=S)

    return evaluate
