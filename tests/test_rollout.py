"""Rolling-upgrade tests: the fast socket-free state machine
(run_rollout against a fake FleetOps with injected canary verdicts,
the rollout-record contract, the same-rid ring-replacement router fix,
the lease seize primitive), plus the slow-tier end-to-end drill — a
live 2-replica fleet + router + canary under load rolled A -> B
(ladder change, zero dropped requests, no key movement) and then
B -> C where C's candidate is provenance-skewed: the canary goes red
and the rollout rolls itself back to B with no operator input."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DESIGNS = os.path.join(ROOT, "raft_tpu", "designs")
SPAR = os.path.join(DESIGNS, "spar_demo.yaml")


# --------------------------------------------------- fast: state machine


class FakeOps:
    """Socket-free FleetOps stand-in: scripted canary verdicts, lease
    seizes modeled as token bumps, every side effect logged."""

    def __init__(self, fleet, verdicts=()):
        self.fleet = {rid: dict(rec) for rid, rec in fleet.items()}
        self.verdicts = list(verdicts)
        self.calls = []
        self._tok = 0

    def live(self):
        return {rid: dict(rec) for rid, rec in self.fleet.items()}

    def spawn_takeover(self, rid, env):
        self.calls.append(("spawn", rid, dict(env or {})))
        return None

    def wait_takeover(self, rid, prev_rec, timeout_s, proc=None):
        self._tok += 1
        rec = dict(prev_rec or {"replica": rid})
        rec["token"] = f"t{self._tok}"
        self.fleet[rid] = rec
        self.calls.append(("seize", rid))
        return rec

    def drain(self, rec):
        self.calls.append(("drain", (rec or {}).get("token")))
        return True

    def canary_baseline(self):
        return {"passes": 0, "fails": 0}

    def canary_verdict(self, baseline, timeout_s, replica=None,
                       endpoint=None):
        ok, why = (self.verdicts.pop(0) if self.verdicts
                   else (True, "canary-green(2)"))
        self.calls.append(("verdict", ok))
        return ok, why


@pytest.fixture()
def releases_ab(tmp_path, monkeypatch):
    """A parent/child release pair (empty entry sets: the bank check is
    trivially clean) with A promoted, plus distinguishable captured
    envs so the tests can see WHICH release's env spawned a replica."""
    from raft_tpu.aot import bank, release

    monkeypatch.setenv("RAFT_TPU_AOT_DIR", str(tmp_path))
    release._PARITY_CACHE[:] = []

    def cut(flags, env, parent=None, promote=False):
        man = release.build_manifest({}, bank.code_fingerprint(), flags,
                                     parent=parent)
        man["env"] = dict(env)
        release.sign_manifest(man)
        os.makedirs(release.releases_dir(), exist_ok=True)
        bank._atomic_write(
            release.manifest_path(man["release"]),
            (json.dumps(man, sort_keys=True) + "\n").encode())
        if promote:
            release.promote(man["release"])
        return man

    a = cut("fa", {"RAFT_TPU_SERVE_MAX_BATCH": "2"}, promote=True)
    b = cut("fb", {"RAFT_TPU_SERVE_MAX_BATCH": "4"},
            parent=a["release"])
    return release, a, b


def _fleet2():
    return {"r0": {"replica": "r0", "port": 1000, "token": "a0"},
            "r1": {"replica": "r1", "port": 1001, "token": "a1"}}


def test_rollout_green_path(releases_ab, tmp_path):
    from raft_tpu.serve import rollout

    release, a, b = releases_ab
    ops = FakeOps(_fleet2())
    record = rollout.run_rollout(str(tmp_path), b["release"],
                                 ["spar=x.yaml"], ops=ops)
    assert record["ok"] and not record["rolled_back"]
    assert record["to"] == b["release"]
    assert record["from"] == a["release"]
    assert record["replaced"] == ["r0", "r1"]
    assert record["aborted"] is None
    assert [s["phase"] for s in record["steps"]] == ["upgrade", "upgrade"]
    assert release.current_release() == b["release"]
    assert release.read_rollout_marker() is None  # cleared on the way out
    # each replica: spawn under the CANDIDATE env -> seize -> drain the
    # old owner -> canary gate, in replica-id order
    spawns = [c for c in ops.calls if c[0] == "spawn"]
    assert [c[1] for c in spawns] == ["r0", "r1"]
    assert all(c[2].get("RAFT_TPU_SERVE_MAX_BATCH") == "4"
               for c in spawns)
    assert [c[0] for c in ops.calls[:4]] == ["spawn", "seize", "drain",
                                             "verdict"]
    drains = [c for c in ops.calls if c[0] == "drain"]
    assert [c[1] for c in drains] == ["a0", "a1"]  # the OLD tokens
    assert rollout.summarize_record(record).startswith(
        f"rollout {b['release']}: upgraded (2 replaced")


def test_rollout_red_canary_rolls_back(releases_ab, tmp_path):
    from raft_tpu.serve import rollout

    release, a, b = releases_ab
    marker_seen = []

    class Ops(FakeOps):
        def canary_verdict(self, baseline, timeout_s, replica=None,
                           endpoint=None):
            # the expected-skew window must be OPEN while steps gate
            marker_seen.append(release.read_rollout_marker())
            return super().canary_verdict(baseline, timeout_s,
                                          replica=replica,
                                          endpoint=endpoint)

    ops = Ops(_fleet2(), verdicts=[(True, "canary-green(2)"),
                                   (False, "canary-parity")])
    record = rollout.run_rollout(str(tmp_path), b["release"],
                                 ["spar=x.yaml"], ops=ops)
    assert not record["ok"] and record["rolled_back"]
    assert record["reason"] == "canary-parity"
    # the postmortem contract: the record NAMES the aborted release
    assert record["aborted"] == b["release"]
    assert record["replaced"] == []
    # automatic rollback: current re-points at the parent, and BOTH
    # touched replicas (the green r0 and the red r1 — its seize may
    # have landed) are re-seized under the PARENT env
    assert release.current_release() == a["release"]
    phases = [(s["phase"], s["replica"]) for s in record["steps"]]
    assert phases == [("upgrade", "r0"), ("upgrade", "r1"),
                      ("rollback", "r0"), ("rollback", "r1")]
    spawns = [c for c in ops.calls if c[0] == "spawn"]
    assert [c[2].get("RAFT_TPU_SERVE_MAX_BATCH") for c in spawns] == \
        ["4", "4", "2", "2"]
    assert release.read_rollout_marker() is None
    assert all(m and m["from"] == a["release"] and m["to"] == b["release"]
               for m in marker_seen)
    assert "rolled back" in rollout.summarize_record(record)


def test_rollout_join_timeout_rolls_back(releases_ab, tmp_path):
    from raft_tpu.serve import rollout

    release, a, b = releases_ab

    class Ops(FakeOps):
        def wait_takeover(self, rid, prev_rec, timeout_s, proc=None):
            if rid == "r0" and release.current_release() == b["release"]:
                return None  # candidate never seized
            return super().wait_takeover(rid, prev_rec, timeout_s, proc)

    record = rollout.run_rollout(str(tmp_path), b["release"],
                                 ["spar=x.yaml"], ops=Ops(_fleet2()))
    assert not record["ok"] and record["reason"] == "join-timeout"
    assert release.current_release() == a["release"]


def test_rollout_refuses_bad_candidate_before_promote(releases_ab,
                                                      tmp_path):
    from raft_tpu.aot import bank
    from raft_tpu.serve import rollout

    release, a, b = releases_ab
    ops = FakeOps(_fleet2())
    with pytest.raises(FileNotFoundError):
        rollout.run_rollout(str(tmp_path), "000000000000",
                            ["spar=x.yaml"], ops=ops)
    # tamper the stored candidate: the preflight refuses BEFORE any
    # promote/spawn — the fleet is untouched
    path = release.manifest_path(b["release"])
    man = json.loads(open(path, encoding="utf-8").read())
    man["flags"] = "tampered"
    bank._atomic_write(path, json.dumps(man).encode())
    with pytest.raises(ValueError, match="refusing to roll out"):
        rollout.run_rollout(str(tmp_path), b["release"],
                            ["spar=x.yaml"], ops=ops)
    assert release.current_release() == a["release"]
    assert ops.calls == []
    assert release.read_rollout_marker() is None


def test_rollout_record_is_run_recorded(releases_ab, tmp_path,
                                        monkeypatch):
    from raft_tpu.serve import rollout

    release, a, b = releases_ab
    runs_dir = tmp_path / "runs"
    monkeypatch.setenv("RAFT_TPU_RUNS_DIR", str(runs_dir))
    rollout.run_rollout(str(tmp_path), b["release"], ["spar=x.yaml"],
                        ops=FakeOps(_fleet2()))
    recs = []
    for name in os.listdir(runs_dir):
        with open(runs_dir / name, encoding="utf-8") as f:
            recs.append(json.load(f))
    mine = [r for r in recs if r.get("kind") == "rollout"]
    assert mine and mine[0]["label"] == b["release"]
    assert mine[0]["extra"]["to"] == b["release"]
    assert mine[0]["extra"]["ok"] is True


# ---------------------------------------- fast: ring replacement + seize


def test_apply_membership_replaced_same_rid_no_key_movement():
    """Satellite regression: a same-rid endpoint change (the rollout
    seize) must count as REPLACED — ring untouched, breaker reset —
    not as an evict+join churning vnodes."""
    from raft_tpu.serve.router import RouterState

    st = RouterState(vnodes=64)
    live = {"r0": {"addr": "127.0.0.1", "port": 1000, "designs": {}},
            "r1": {"addr": "127.0.0.1", "port": 1001, "designs": {}}}
    assert st.apply_membership(live) == (["r0", "r1"], [], [])
    keys = [f"sig{i}|fp{i}" for i in range(64)]
    before = {k: st.owners(k) for k in keys}
    # open r0's breaker, then seize: new endpoint, same rid
    for _ in range(8):
        st.record_failure("r0", "connect")
    assert st.breaker_states().get("r0") == "open"
    live2 = {"r0": {"addr": "127.0.0.1", "port": 2000, "designs": {}},
             "r1": dict(live["r1"])}
    added, removed, replaced = st.apply_membership(live2)
    assert (added, removed, replaced) == ([], [], ["r0"])
    # zero key movement: every owner list is byte-identical
    assert {k: st.owners(k) for k in keys} == before
    # the new process starts with a CLOSED breaker (old failures were
    # the old process's)
    assert st.breaker_states().get("r0") == "closed"
    assert st.endpoint("r0") == ("127.0.0.1", 2000)
    # an unchanged membership pass reports nothing
    assert st.apply_membership(live2) == ([], [], [])


def test_canary_prune_voids_replaced_endpoint_stamp(tmp_path,
                                                    monkeypatch):
    """The takeover-race regression: the canary's last observation of
    a rid can predate its seize.  Once membership shows the rid at a
    NEW endpoint, the old-endpoint stamp must be voided — otherwise
    parity red-flags the fleet for one probe interval exactly as the
    rollout's expected-skew window closes."""
    from raft_tpu.aot import bank, release
    from raft_tpu.serve.canary import CanaryState

    monkeypatch.setenv("RAFT_TPU_AOT_DIR", str(tmp_path))
    release._PARITY_CACHE[:] = []
    sha_b = "b" * 16
    man = release.build_manifest({"k": {"payload_sha256": sha_b * 4}},
                                 "code", "flags")
    release.sign_manifest(man)
    os.makedirs(release.releases_dir(), exist_ok=True)
    bank._atomic_write(release.manifest_path(man["release"]),
                       (json.dumps(man, sort_keys=True) + "\n").encode())
    release.promote(man["release"])

    st = CanaryState(rtol=1e-6, atol=1e-9)
    stamp_new = {"release": man["release"], "bank_sha": sha_b,
                 "bank_key": "k", "code": "code", "flags": "flags"}
    stamp_old = dict(stamp_new, release="aaaaaaaaaaaa",
                     bank_sha="a" * 16)
    # r1's stamp was probed from its pre-takeover endpoint; r0 is
    # already on the new release.  No rollout marker: allowed = {new}.
    st.observe("spar", "r1", "fp", (4.0, 9.0, 0.0), ("status",), {},
               0, provenance=stamp_old, endpoint="127.0.0.1:1001")
    st.observe("spar", "r0", "fp", (4.0, 9.0, 0.0), ("status",), {},
               0, provenance=stamp_new, endpoint="127.0.0.1:1000")
    assert st.summary()["provenance"]["consistent"] is False
    # membership now shows r1 at its post-seize endpoint: the stale
    # stamp is void, parity green WITHOUT waiting for r1's next probe
    live = {"r0": {"addr": "127.0.0.1", "port": 1000},
            "r1": {"addr": "127.0.0.1", "port": 2001}}
    assert st.prune(live) is True
    summ = st.summary()
    assert summ["provenance"]["consistent"] is True
    assert summ["parity_ok"] is True
    # same-endpoint membership is NOT a takeover: nothing dropped
    st.observe("spar", "r1", "fp", (4.0, 9.0, 0.0), ("status",), {},
               0, provenance=stamp_new, endpoint="127.0.0.1:2001")
    assert st.prune(live) is False
    assert st.summary()["provenance"]["consistent"] is True
    # plain-iterable membership (replica-id only) still prunes departures
    assert st.prune(["r1"]) is True   # r0 left the fleet
    assert st.prune(["r1"]) is False


def test_canary_verdict_requires_probes_of_the_new_endpoint(
        tmp_path, monkeypatch):
    """The green-without-probing regression, both flavors: fleet-wide
    fresh passes accrue from the candidate's healthy neighbors, and
    per-rid probe counts accrue from the OLD process still answering
    its drain window while the canary's membership snapshot is a beat
    stale.  The gate must count the canary's observation run AT the
    post-seize endpoint — the process identity."""
    from raft_tpu.serve import rollout

    monkeypatch.setenv("RAFT_TPU_ROLLOUT_CANARY_PROBES", "2")
    monkeypatch.setenv("RAFT_TPU_ROLLOUT_POLL_S", "0.01")
    payloads = []

    def fake_get(url, path, timeout_s=5.0):
        return payloads.pop(0) if len(payloads) > 1 else payloads[0]

    monkeypatch.setattr(rollout, "_http_get_json", fake_get)
    ops = rollout.FleetOps(str(tmp_path), ["spar=x.yaml"],
                           router_url="http://127.0.0.1:1")
    base = {"passes": 10, "fails": 0}
    new_ep = "127.0.0.1:2000"

    def can(passes, probes):
        return {"canary": {"passes": passes, "fails": 0,
                           "parity_ok": True, "probes": probes},
                "active": []}

    # neighbors rack up fleet-wide passes AND the draining old process
    # at :1000 keeps answering probes: neither may green the gate
    stale = {"r0": {"endpoint": "127.0.0.1:1000", "n": 7},
             "r1": {"endpoint": "127.0.0.1:1001", "n": 13}}
    payloads[:] = [can(30, stale)]
    ok, why = ops.canary_verdict(base, timeout_s=0.05, replica="r0",
                                 endpoint=new_ep)
    assert (ok, why) == (False, "canary-timeout")
    # the canary's run restarted at the new endpoint: its count IS the
    # new process's probe count — 2 observations = green
    payloads[:] = [can(31, {"r0": {"endpoint": new_ep, "n": 1}}),
                   can(32, {"r0": {"endpoint": new_ep, "n": 2}})]
    ok, why = ops.canary_verdict(base, timeout_s=5.0, replica="r0",
                                 endpoint=new_ep)
    assert ok is True and why == "canary-green(2)"
    # no replica/endpoint named (API compat): global fresh passes gate
    payloads[:] = [can(18, {})]
    ok, why = ops.canary_verdict(base, timeout_s=5.0)
    assert ok is True and why == "canary-green(8)"
    # fresh fails anywhere stay an immediate red regardless of probes
    payloads[:] = [{"canary": {"passes": 30, "fails": 1,
                               "parity_ok": True,
                               "probes": {"r0": {"endpoint": new_ep,
                                                 "n": 9}}},
                    "active": []}]
    ok, why = ops.canary_verdict(base, timeout_s=5.0, replica="r0",
                                 endpoint=new_ep)
    assert (ok, why) == (False, "canary-fail")


def test_fleet_seize_takes_over_lease(tmp_path):
    from raft_tpu.serve.fleet import FleetLedger

    old = FleetLedger(str(tmp_path), replica_id="r0")
    assert old.claim(port=1000, designs={"spar": {}})
    prev = old.read("r0")[0]
    new = FleetLedger(str(tmp_path), replica_id="r0")
    assert new.seize(port=2000, designs={"spar": {}})
    rec = new.read("r0")[0]
    assert rec["port"] == 2000 and rec["token"] == new.token
    assert rec["token"] != prev["token"]
    # the dispossessed owner's renew/release no-op on token mismatch —
    # membership never flaps back to the old endpoint
    assert not old.renew()
    assert not old.release()
    assert new.read("r0")[0]["port"] == 2000
    # exactly one live lease, same rid throughout
    assert sorted(FleetLedger(str(tmp_path)).live()) == ["r0"]


# ------------------------------------------------- slow: the real drill


@pytest.fixture(scope="module")
def release_bank(tmp_path_factory):
    """Warm the spar serve programs under ladder A (max batch 2) and
    cut + promote release A — the fleet's starting state."""
    base = tmp_path_factory.mktemp("release_bank")
    bank, cache = str(base / "bank"), str(base / "jax_cache")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               RAFT_TPU_SERVE_MAX_BATCH="2",
               # pow2, not the cost-pruned default: refinement reads
               # the bank's cost ledger, so a SECOND replica warming
               # after the first could prune differently — a per-
               # replica ladder split is exactly what the parity
               # canary alarms on, and this drill needs it QUIET
               # outside the poisoned window
               RAFT_TPU_SERVE_LADDER="pow2",
               RAFT_TPU_AOT="load", RAFT_TPU_AOT_DIR=bank,
               RAFT_TPU_CACHE_DIR=cache)
    for drop in ("RAFT_TPU_LOG", "RAFT_TPU_FAULTS", "RAFT_TPU_AOT_MISS",
                 "RAFT_TPU_COMPILE_BUDGET", "RAFT_TPU_RUNS_DIR"):
        env.pop(drop, None)
    proc = subprocess.run(
        [sys.executable, "-m", "raft_tpu.aot", "warmup", "--kinds",
         "serve", "--design", SPAR],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    rel_a = _cut_release(env, promote=True)
    return {"bank": bank, "cache": cache, "env": env, "A": rel_a}


def _cut_release(env, promote=False, label=None):
    argv = [sys.executable, "-m", "raft_tpu.aot", "release", "cut"]
    if promote:
        argv.append("--promote")
    if label:
        argv += ["--label", label]
    proc = subprocess.run(argv, cwd=ROOT, env=env, capture_output=True,
                          text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # "release <id> cut: N entries, parent X (<dir>)"
    return proc.stdout.split("release ", 1)[1].split()[0]


def _drill_env(warm, logdir, max_batch="2", extra=None):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               RAFT_TPU_SERVE_TICK_MS="10",
               RAFT_TPU_SERVE_LADDER="pow2",
               RAFT_TPU_SERVE_MAX_BATCH=max_batch,
               RAFT_TPU_SERVE_DRAIN_S="20",
               RAFT_TPU_FLEET_TTL_S="3",
               RAFT_TPU_AOT="require",
               RAFT_TPU_COMPILE_BUDGET="0",
               RAFT_TPU_AOT_DIR=warm["bank"],
               RAFT_TPU_CACHE_DIR=warm["cache"],
               RAFT_TPU_CANARY_S="0.5",
               RAFT_TPU_LOG=str(logdir) + os.sep)
    for drop in ("RAFT_TPU_FAULTS", "RAFT_TPU_RUNS_DIR"):
        env.pop(drop, None)
    env.update(extra or {})
    return env


def _spawn_replica(root, rid, env, out_path):
    with open(out_path, "ab") as logf:
        return subprocess.Popen(
            [sys.executable, "-m", "raft_tpu.serve",
             "--designs", f"spar={SPAR}", "--port", "0",
             "--fleet-dir", str(root), "--replica-id", rid],
            cwd=ROOT, env=env, stdout=logf, stderr=subprocess.STDOUT)


def _wait_live(root, rids, deadline_s=300):
    from raft_tpu.serve.fleet import FleetLedger

    ledger = FleetLedger(str(root))
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        live = ledger.live()
        if set(rids) <= set(live):
            return live
        time.sleep(0.3)
    raise AssertionError(f"replicas {rids} never joined: "
                         f"{sorted(ledger.live())}")


def _spawn_router(root, env, extra=None):
    renv = dict(env)
    renv.update({"RAFT_TPU_ROUTER_PROBE_S": "0.4",
                 "RAFT_TPU_ROUTER_RETRIES": "5",
                 "RAFT_TPU_ROUTER_BACKOFF_MS": "25",
                 "RAFT_TPU_ROUTER_BACKOFF_CAP_MS": "400",
                 "RAFT_TPU_ROUTER_TIMEOUT_S": "120"})
    renv.update(extra or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "raft_tpu.serve", "router",
         "--fleet-dir", str(root), "--port", "0"],
        cwd=ROOT, env=renv, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    t0 = time.monotonic()
    for line in proc.stdout:
        if "routing" in line and "http://" in line:
            port = int(line.split("http://", 1)[1].split()[0]
                       .rsplit(":", 1)[1])
            return proc, port
        if time.monotonic() - t0 > 120:
            break
    raise AssertionError("router never printed its ready line")


def _stop_pid(pid, deadline_s=60):
    """SIGTERM a (possibly non-child) process and wait for it to
    vanish — rollout candidates are the DRIVER's children, not ours."""
    try:
        os.kill(pid, signal.SIGTERM)
    except ProcessLookupError:
        return True
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return True
        time.sleep(0.3)
    return False


def _parse_record(stdout):
    """The rollout CLI prints the record as indented JSON followed by
    the one-line summary — raw_decode eats exactly the JSON."""
    return json.JSONDecoder().raw_decode(stdout)[0]


def _read_events(logdir):
    events = []
    for name in os.listdir(logdir):
        if name.endswith(".jsonl"):
            with open(os.path.join(logdir, name)) as f:
                for line in f:
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        pass
    return events


def _replica_release(port, timeout=60):
    """One direct probe at a replica endpoint; the release id its
    provenance stamp carries."""
    from raft_tpu.serve.client import ServeClient

    c = ServeClient("127.0.0.1", port, timeout=timeout)
    try:
        code, _ = c.evaluate("spar", 5.0, 10.0, 0.0)
        assert code in (200, 422), code
        return (c.last_provenance or {}).get("release")
    finally:
        c.close()


@pytest.mark.slow
def test_rolling_upgrade_and_automatic_rollback_drill(release_bank,
                                                      tmp_path):
    """THE release acceptance drill, one fleet end to end:

    1. 2 replicas on release A (ladder max 2) + router + canary +
       alert engine, steady load green;
    2. warm ladder B (max batch 4 — ONE new program), cut release B,
       roll A -> B under continuous load: zero dropped/5xx responses,
       both replicas replaced in place (<= N ring updates, no evict),
       the fleet's provenance converges on B, the driver + replicas
       merge onto one trace with 0 orphan spans;
    3. cut release C whose captured env arms provenance_skew (the
       deterministic stale-candidate stand-in), roll B -> C: the
       canary goes RED on the skewed candidate, the rollout rolls
       back to B automatically, the fleet converges on B, the run
       record names the aborted C sha, and canary-parity fired only
       during the bad window."""
    from raft_tpu.aot import release as release_mod
    from raft_tpu.serve.client import ServeClient
    from raft_tpu.serve.fleet import FleetLedger

    warm = release_bank
    rel_a = warm["A"]
    logdir = tmp_path / "logs"
    logdir.mkdir()
    root = tmp_path / "deploy"
    runs_dir = tmp_path / "runs"
    alert_sink = tmp_path / "alerts.jsonl"
    # the alert pack trimmed to the canary rules: a draining old owner
    # mid-takeover may legitimately bounce a breaker, and this drill's
    # contract is "the CANARY gates the rollout" — the default pack's
    # breaker rules have their own drill in test_router
    rules_path = tmp_path / "rules.json"
    rules_path.write_text(json.dumps({"rules": [
        {"name": n, "disabled": True}
        for n in ("slo-breach", "breaker-storm", "lease-churn",
                  "cache-hit-collapse", "compile-budget-burn")]}))

    env_a = _drill_env(warm, logdir, max_batch="2")
    results, errors = [], []
    stop_load = threading.Event()

    def loader(i, port):
        cl = ServeClient("127.0.0.1", port, client_id=f"load-{i}",
                         timeout=300)
        j = 0
        try:
            while not stop_load.is_set():
                code, _ = cl.evaluate("spar", 4.0 + 0.01 * ((i + j) % 40),
                                      9.0 + 0.01 * (j % 30), 0.0)
                results.append(code)
                j += 1
                time.sleep(0.05)
        except Exception as e:  # noqa: BLE001 — asserted below
            errors.append((i, repr(e)))
        finally:
            cl.close()

    procs = {}
    loaders = []
    try:
        procs["r0"] = _spawn_replica(root, "r0", env_a,
                                     tmp_path / "r0.out")
        procs["r1"] = _spawn_replica(root, "r1", env_a,
                                     tmp_path / "r1.out")
        _wait_live(root, {"r0", "r1"})
        router_proc, port = _spawn_router(
            root, env_a,
            extra={"RAFT_TPU_ALERT_EVAL_S": "0.25",
                   "RAFT_TPU_ALERT_RULES": str(rules_path),
                   "RAFT_TPU_ALERTS": str(alert_sink)})
        procs["router"] = router_proc
        leases0 = FleetLedger(str(root)).live()
        assert all(_replica_release(leases0[r]["port"]) == rel_a
                   for r in ("r0", "r1"))

        # ---- phase 2: warm ladder B, cut B, roll the live fleet
        warm_b_env = dict(warm["env"], RAFT_TPU_SERVE_MAX_BATCH="4",
                          RAFT_TPU_AOT="load")
        proc = subprocess.run(
            [sys.executable, "-m", "raft_tpu.aot", "warmup", "--kinds",
             "serve", "--design", SPAR],
            cwd=ROOT, env=warm_b_env, capture_output=True, text=True,
            timeout=900)
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr
        rel_b = _cut_release(warm_b_env, label="ladder max-batch 4")
        assert rel_b != rel_a

        for i in range(4):
            t = threading.Thread(target=loader, args=(i, port))
            t.start()
            loaders.append(t)
        time.sleep(2.0)  # steady load before the rollout

        driver_env = dict(env_a,
                          RAFT_TPU_RUNS_DIR=str(runs_dir),
                          RAFT_TPU_ROLLOUT_CANARY_PROBES="2",
                          RAFT_TPU_ROLLOUT_POLL_S="0.3",
                          RAFT_TPU_ROLLOUT_HEALTH_TIMEOUT_S="300")
        drv = subprocess.run(
            [sys.executable, "-m", "raft_tpu.serve", "rollout",
             "--fleet-dir", str(root), "--to", rel_b,
             "--designs", f"spar={SPAR}",
             "--router-url", f"http://127.0.0.1:{port}"],
            cwd=ROOT, env=driver_env, capture_output=True, text=True,
            timeout=900)
        assert drv.returncode == 0, drv.stdout + drv.stderr
        record = _parse_record(drv.stdout)
        assert record["ok"] and record["replaced"] == ["r0", "r1"]
        assert not record["rolled_back"]
        assert release_mod.current_release(warm["bank"]) == rel_b

        # both replicas were replaced IN PLACE: same rids, new pids,
        # zero compiles (ladder 1,2,4 all banked), provenance all B
        leases_b = _wait_live(root, {"r0", "r1"})
        assert {leases_b[r]["pid"] for r in leases_b} \
            != {leases0[r]["pid"] for r in leases0}
        for rid in ("r0", "r1"):
            hc = ServeClient("127.0.0.1", leases_b[rid]["port"],
                             timeout=60)
            code, health = hc.healthz()
            hc.close()
            assert code == 200
            assert health["xla_real_compiles"] == 0
            assert health["aot_programs_compiled"] == 0
            assert _replica_release(leases_b[rid]["port"]) == rel_b

        # ---- phase 3: a poisoned candidate C rolls itself back.
        # C shares B's bank view (parent=B differentiates the id); its
        # captured env additionally arms the provenance-skew fault —
        # the deterministic stand-in for a stale-banked candidate.
        # env is signed but NOT part of the content address, so the
        # manifest still verifies: exactly the "bad release ships a
        # bad environment" hole the canary gate exists to catch.
        rel_c = _cut_release(dict(warm_b_env), label="poisoned")
        assert rel_c not in (rel_a, rel_b)
        man_path = os.path.join(warm["bank"], "releases",
                                f"{rel_c}.json")
        man = json.loads(open(man_path, encoding="utf-8").read())
        man["env"]["RAFT_TPU_FAULTS"] = \
            "provenance_skew:serve_provenance"
        sys.path.insert(0, ROOT)
        from raft_tpu.aot.release import sign_manifest

        with open(man_path, "w", encoding="utf-8") as f:
            json.dump(sign_manifest(man), f)
        t_bad = time.time()
        drv2 = subprocess.run(
            [sys.executable, "-m", "raft_tpu.serve", "rollout",
             "--fleet-dir", str(root), "--to", rel_c,
             "--designs", f"spar={SPAR}",
             "--router-url", f"http://127.0.0.1:{port}"],
            cwd=ROOT, env=driver_env, capture_output=True, text=True,
            timeout=900)
        assert drv2.returncode == 1, drv2.stdout + drv2.stderr
        record2 = _parse_record(drv2.stdout)
        assert record2["rolled_back"] and not record2["ok"]
        assert record2["aborted"] == rel_c       # the postmortem sha
        # the parity split reaches the verdict through whichever gate
        # reads it first: the canary_fail counter (a parity-split probe
        # counts as a fail), the parity gauge, or the fired alert
        assert record2["reason"] in ("canary-fail", "canary-parity",
                                     "alert:canary-parity",
                                     "alert:canary-failure"), record2
        # automatic convergence back on B: pointer, leases, provenance
        assert release_mod.current_release(warm["bank"]) == rel_b
        leases_c = _wait_live(root, {"r0", "r1"})
        for rid in ("r0", "r1"):
            assert _replica_release(leases_c[rid]["port"]) == rel_b

        stop_load.set()
        for t in loaders:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in loaders)
        # ZERO dropped requests across BOTH rollouts: every response
        # resolved 200/422, never a 5xx and never a raised socket error
        assert not errors, errors
        assert results and all(c in (200, 422) for c in results), \
            sorted({c for c in results if c not in (200, 422)})

        # ---- teardown: drain the final fleet (driver-spawned pids are
        # not our children), stop the router
        for rid in ("r0", "r1"):
            assert _stop_pid(leases_c[rid]["pid"])
        router_proc.send_signal(signal.SIGTERM)
        assert router_proc.wait(timeout=60) == 0
    finally:
        stop_load.set()
        for rid, p in procs.items():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs.values():
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
        for rec in FleetLedger(str(root)).live().values():
            _stop_pid(rec.get("pid") or 0, deadline_s=10)

    # ---- event-stream assertions
    events = _read_events(logdir)
    names = [e.get("event") for e in events]
    # surf replacement, not churn: every takeover is ONE same-rid ring
    # update (<= N per rollout), and the seize path never evicted
    ring_updates = [e for e in events
                    if e.get("event") == "router_ring_update"]
    replaced_updates = [e for e in ring_updates if e.get("replaced")]
    # A->B replaced r0+r1; B->C replaced r0, rollback re-replaced r0
    assert len(replaced_updates) == 4, replaced_updates
    assert all(len(e["replaced"]) == 1 for e in replaced_updates)
    assert names.count("replica_takeover") == 4
    assert names.count("replica_evict") == 0
    assert names.count("rollout_start") == 2
    assert names.count("rollout_rollback") == 1
    aborted = [e for e in events if e.get("event") == "rollout_rollback"]
    assert aborted[0]["aborted"] == rel_c
    assert names.count("release_promote") >= 3   # A->B, B->C, C->B
    # the replicas resolved their release at startup
    assert names.count("release_resolve") >= 4

    # ---- the run records name both rollouts
    runs = []
    for name in os.listdir(runs_dir):
        with open(runs_dir / name, encoding="utf-8") as f:
            runs.append(json.load(f))
    rollouts = {r["label"]: r for r in runs if r.get("kind") == "rollout"}
    assert rollouts[rel_b]["extra"]["ok"] is True
    assert rollouts[rel_c]["extra"]["aborted"] == rel_c

    # ---- the canary alert fired during, and only during, the bad
    # window (phase 1/2 steady+rollout state must be alert-free)
    from raft_tpu.obs.alerts import read_sink

    records, bad = read_sink(str(alert_sink))
    assert bad == 0
    fires = [r for r in records if r["kind"] == "fire"]
    assert fires, "the poisoned candidate never tripped an alert"
    # the skew trips BOTH canary rules (a parity-split probe also
    # counts against the golden-failure counter) and nothing else;
    # canary-parity — the version-aware rule — must be among them
    assert {r["rule"] for r in fires} <= {"canary-parity",
                                          "canary-failure"}, fires
    assert "canary-parity" in {r["rule"] for r in fires}, fires
    assert min(r["t_unix"] for r in fires) >= t_bad - 0.5, \
        ("an alert fired before the poisoned rollout", t_bad, fires)

    # ---- one merged timeline: the rollout driver's span tree adopts
    # every spawned replica via traceparent propagation — 0 orphans,
    # every span balanced (all processes exited cleanly)
    merged = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "trace", "--merge",
         str(logdir), "-o", str(tmp_path / "merged.json"), "--check"],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert merged.returncode == 0, merged.stdout + merged.stderr
    meta = json.loads((tmp_path / "merged.json").read_text())["otherData"]
    assert meta["spans_orphaned"] == 0, meta
    rollout_spans = [e for e in events if e.get("event") == "span_begin"
                     and e.get("name") == "rollout"]
    assert len(rollout_spans) == 2


@pytest.mark.slow
def test_stale_bank_fails_fast_with_diagnosis(release_bank, tmp_path):
    """Fail fast on stale banks: a require-mode replica whose ladder
    outgrew the bank must exit 3 naming the unwarmed programs, the
    mismatch class, and the exact warmup command — and `release
    verify --against-designs` gives the same diagnosis standalone."""
    logdir = tmp_path / "logs"
    logdir.mkdir()
    root = tmp_path / "deploy"
    # ladder max 8: rows=8 was never warmed under release A/B
    env = _drill_env(release_bank, logdir, max_batch="8")
    out = tmp_path / "stale.out"
    proc = _spawn_replica(root, "rX", env, out)
    rc = proc.wait(timeout=600)
    assert rc == 3, (rc, out.read_text()[-2000:])
    text = out.read_text()
    assert "UNWARMED" in text
    assert "why [ladder]" in text or "why [avals]" in text
    assert "python -m raft_tpu.aot warmup --kinds serve" in text
    assert "release cut --promote" in text
    # no half-joined lease left behind
    from raft_tpu.serve.fleet import FleetLedger

    assert "rX" not in FleetLedger(str(root)).replicas()
    # the standalone preflight agrees, exit 1
    verify = subprocess.run(
        [sys.executable, "-m", "raft_tpu.aot", "release", "verify",
         "--against-designs", f"spar={SPAR}"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=600)
    assert verify.returncode == 1, verify.stdout + verify.stderr
    assert "UNWARMED" in verify.stderr


@pytest.mark.slow
def test_autoscaler_actuators_against_real_fleet(release_bank, tmp_path):
    """The autoscaler's REAL actuators (policy hysteresis is unit-
    tested in test_autoscale): a scripted hot signal spawns a replica
    that joins from the warm bank with zero compiles; a scripted cold
    signal drains the newest joiner back out.  On this 1-core host
    this proves the control loop, not a throughput win."""
    from raft_tpu.serve.autoscale import Autoscaler, FleetBackend
    from raft_tpu.serve.client import ServeClient
    from raft_tpu.serve.fleet import FleetLedger

    logdir = tmp_path / "logs"
    logdir.mkdir()
    root = tmp_path / "deploy"
    env = _drill_env(release_bank, logdir, max_batch="2")
    procs = {}
    try:
        procs["r0"] = _spawn_replica(root, "r0", env,
                                     tmp_path / "r0.out")
        _wait_live(root, {"r0"})

        class ScriptedBackend(FleetBackend):
            press_now = 0.0
            occ_now = 1.0

            def pressure(self):
                return self.press_now

            def occupancy(self):
                return self.occ_now

        # the spawned replica must inherit the fleet env (bank, ladder,
        # require-mode) — the backend spawn path merges os.environ
        old_env = dict(os.environ)
        os.environ.update({k: v for k, v in env.items()
                           if k.startswith(("RAFT_TPU_", "JAX_", "XLA_"))})
        try:
            backend = ScriptedBackend(str(root), [f"spar={SPAR}"])
            clock = [0.0]
            scaler = Autoscaler(backend=backend, clock=lambda: clock[0],
                                interval_s=1.0, minimum=1, maximum=2,
                                cooldown_s=0.0)
            monkey_env = {"RAFT_TPU_AUTOSCALE_OUT_FOR_S": "1",
                          "RAFT_TPU_AUTOSCALE_IN_FOR_S": "1"}
            # rebuild the private engine under short windows
            os.environ.update(monkey_env)
            from raft_tpu.obs.alerts import AlertEngine
            from raft_tpu.serve.autoscale import scaling_rules

            scaler.engine = AlertEngine(rules=scaling_rules(),
                                        sink_path=None,
                                        clock=lambda: clock[0])
            # scale OUT on sustained pressure
            ScriptedBackend.press_now = 1.0
            clock[0] = 0.0
            assert scaler.step(now=0.0) is None
            clock[0] = 1.5
            act = scaler.step(now=1.5)
            assert act is not None and act[0] == "out"
            new_rid = act[1]
            live = _wait_live(root, {"r0", new_rid})
            hc = ServeClient("127.0.0.1", live[new_rid]["port"],
                             timeout=60)
            code, health = hc.healthz()
            hc.close()
            assert code == 200
            assert health["xla_real_compiles"] == 0
            assert health["aot_programs_compiled"] == 0
            # scale IN on sustained low occupancy: the NEWEST joiner
            # (the autoscaler's own spawn) drains first
            ScriptedBackend.press_now = 0.0
            ScriptedBackend.occ_now = 0.0
            clock[0] = 10.0
            assert scaler.step(now=10.0) is None
            clock[0] = 11.5
            act = scaler.step(now=11.5)
            assert act == ("in", new_rid)
            t0 = time.monotonic()
            while time.monotonic() - t0 < 120:
                if sorted(FleetLedger(str(root)).live()) == ["r0"]:
                    break
                time.sleep(0.3)
            assert sorted(FleetLedger(str(root)).live()) == ["r0"]
            for p in backend._procs:
                assert p.wait(timeout=60) == 0  # drained clean exit
        finally:
            os.environ.clear()
            os.environ.update(old_env)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs.values():
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()

    events = _read_events(logdir)
    names = [e.get("event") for e in events]
    assert names.count("autoscale_out") == 1
    assert names.count("autoscale_in") == 1
