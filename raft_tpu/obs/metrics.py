"""Process-wide, thread-safe metrics registry.

Counters, gauges and fixed-bucket histograms fed by the runtime's
existing event sites (shards done/retried/quarantined/escalated, XLA
backend compiles via the recompilation sentinel, drag-linearisation
iteration counts, solver-health flags, span wall times).  Unlike the
JSONL event stream — which answers "what happened, in order" — the
registry answers "how much, in total" without re-reading anything:
``snapshot()`` is dumped into the sweep manifest and
``<out_dir>/metrics.json`` at ``sweep_done``, the bench folds it into
its breakdown, and :func:`to_prometheus` renders the standard
text-exposition format for scraping long runs
(``RAFT_TPU_METRICS=<path>``).

Pure stdlib, no jax import.  Metric updates are a lock + int/float op:
cheap enough to stay on unconditionally (they fire per shard / per
retry / per case, never per frequency bin), so telemetry totals exist
even when the ``RAFT_TPU_LOG`` event stream is off.

Histogram buckets are fixed and log-spaced (4 per decade over
1e-6..1e7, covering microsecond spans to ~100-day walls and iteration
counts alike) so snapshots from different processes are mergeable and
the p50/p95 estimates are stable.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque

from raft_tpu.utils import config

_T0 = time.perf_counter()

# fixed log-spaced bucket upper bounds: 10^(-6) .. 10^7, 4 per decade
BUCKET_BOUNDS = tuple(10.0 ** (e / 4.0) for e in range(-24, 29))


def _exemplar_limits():
    """(K, min_value) admission policy for histogram/window exemplars,
    re-read per observation so tests and operators can retune live."""
    try:
        k = int(config.get("EXEMPLAR_K"))
    except ValueError:
        k = 2
    try:
        vmin = float(config.get("EXEMPLAR_MIN_S"))
    except ValueError:
        vmin = 0.0
    return k, vmin


def _emit_exemplar_event(metric, v, labels):
    """One ``exemplar_recorded`` event per *admitted* exemplar — the
    join key ``obs report --tail`` uses to find "the actual p99
    request" in a capture.  Called outside the metric lock (log_event
    takes the sink lock; never hold both).  Lazy import: metrics must
    stay importable standalone."""
    from raft_tpu.utils import structlog

    structlog.log_event("exemplar_recorded", metric=metric,
                        value=round(float(v), 6), **labels)


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0  # raft-lint: guarded-by=self._lock

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def snapshot(self):
        return self._value


class Gauge:
    """Last-value gauge with a high watermark (heartbeat memory peaks
    survive in ``max`` even after the gauge drops back)."""

    __slots__ = ("name", "_lock", "_value", "_max")

    def __init__(self, name):
        self.name = name
        self._lock = threading.Lock()
        self._value = None  # raft-lint: guarded-by=self._lock
        self._max = None  # raft-lint: guarded-by=self._lock

    def set(self, v):
        v = float(v)
        with self._lock:
            self._value = v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def value(self):
        return self._value

    @property
    def max(self):
        return self._max

    def snapshot(self):
        return {"value": self._value, "max": self._max}


class Histogram:
    """Fixed log-spaced-bucket histogram with count/sum/min/max and
    bucket-interpolated percentile estimates."""

    __slots__ = ("name", "_lock", "count", "sum", "min", "max", "_buckets",
                 "_exemplars")

    def __init__(self, name):
        self.name = name
        self._lock = threading.Lock()
        self.count = 0  # raft-lint: guarded-by=self._lock
        self.sum = 0.0  # raft-lint: guarded-by=self._lock
        self.min = None  # raft-lint: guarded-by=self._lock
        self.max = None  # raft-lint: guarded-by=self._lock
        # len(BUCKET_BOUNDS) + 1: trailing overflow bucket (+inf)
        self._buckets = [0] * (len(BUCKET_BOUNDS) + 1)  # raft-lint: guarded-by=self._lock
        # bucket index -> up to K (value, unix_t, labels) kept largest-
        # first, so "the actual p99 request" is nameable from /metrics
        self._exemplars: dict = {}  # raft-lint: guarded-by=self._lock

    def observe(self, v, exemplar=None):
        """Record ``v``; ``exemplar`` (a small dict of label strings —
        trace/span ids plus caller attrs) competes for one of the
        top-K-by-value exemplar slots of ``v``'s log-bucket."""
        v = float(v)
        i = bisect.bisect_left(BUCKET_BOUNDS, v)
        admitted = False
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            self._buckets[i] += 1
            if exemplar is not None:
                # top-K-by-value admission for bucket i
                k, vmin = _exemplar_limits()
                if k > 0 and v >= vmin:
                    slot = self._exemplars.setdefault(i, [])
                    entry = (v, time.time(), dict(exemplar))
                    if len(slot) < k:
                        slot.append(entry)
                        admitted = True
                    else:
                        jmin = min(range(len(slot)),
                                   key=lambda j: slot[j][0])
                        if v > slot[jmin][0]:
                            slot[jmin] = entry
                            admitted = True
        if admitted:
            # outside the lock: log_event takes the sink lock, and the
            # two must never nest
            _emit_exemplar_event(self.name, v, exemplar)

    def exemplars(self):
        """``{bucket_index: (value, unix_t, labels)}`` — the single
        best (largest) exemplar per occupied bucket, for the
        OpenMetrics exporter."""
        with self._lock:
            return {i: max(slot, key=lambda e: e[0])
                    for i, slot in self._exemplars.items() if slot}

    def percentile(self, p):
        """Estimated p-quantile (0..1) from the bucket counts: the
        upper bound of the bucket where the cumulative count crosses
        ``p * count``, clamped to the observed min/max."""
        with self._lock:
            if not self.count:
                return None
            target = p * self.count
            acc = 0
            for i, n in enumerate(self._buckets):
                acc += n
                if acc >= target and n:
                    hi = (BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS)
                          else self.max)
                    if self.min is None or self.max is None or hi is None:
                        return None if hi is None else float(hi)
                    return float(min(max(hi, self.min), self.max))
            return None if self.max is None else float(self.max)

    def snapshot(self):
        with self._lock:
            if not self.count:
                return {"count": 0}
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "mean": round(self.sum / self.count, 6),
            "min": round(self.min, 6),
            "max": round(self.max, 6),
            "p50": round(self.percentile(0.50), 6),
            "p95": round(self.percentile(0.95), 6),
        }

    def buckets(self):
        """(upper_bound, cumulative_count) pairs for the Prometheus
        exporter (only buckets up to the last non-empty one, plus
        +Inf)."""
        with self._lock:
            counts = list(self._buckets)
        out, acc = [], 0
        for bound, n in zip(BUCKET_BOUNDS, counts):
            acc += n
            out.append((bound, acc))
        return out

    def state(self):
        """JSON-portable raw state (sparse bucket counts) for
        cross-process pooling: fabric workers publish their
        ``shard_wall_s`` state in the ledger's worker status files, and
        a stealer merges every worker's state (:func:`merge_states`) to
        get the fleet-wide p95 the straggler threshold needs — bucket
        counts add exactly, unlike p95s."""
        with self._lock:
            return {
                "count": self.count,
                "sum": self.sum,
                "min": self.min,
                "max": self.max,
                "buckets": {str(i): n for i, n in enumerate(self._buckets)
                            if n},
            }

    def merge_state(self, state):
        """Fold one :meth:`state` dict (from another process) into this
        histogram.  Unknown/garbled states are ignored rather than
        poisoning the pool — a steal decision must never crash on a
        half-written status file."""
        try:
            count = int(state["count"])
            if count <= 0:
                return
            buckets = {int(i): int(n)
                       for i, n in (state.get("buckets") or {}).items()}
            smin = (None if state.get("min") is None
                    else float(state["min"]))
            smax = (None if state.get("max") is None
                    else float(state["max"]))
            ssum = float(state.get("sum", 0.0))
            if smin is None or smax is None:
                # count>0 with no extrema (schema drift / stringified
                # payload): fall back to the occupied buckets' bounds
                # so percentile() always has a clamp range
                occupied = [i for i, n in buckets.items()
                            if n and 0 <= i <= len(BUCKET_BOUNDS)]
                if not occupied:
                    return
                smin = BUCKET_BOUNDS[max(min(occupied) - 1, 0)]
                smax = BUCKET_BOUNDS[min(max(occupied),
                                         len(BUCKET_BOUNDS) - 1)]
        except (KeyError, TypeError, ValueError):
            return
        with self._lock:
            self.count += count
            self.sum += ssum
            if self.min is None or smin < self.min:
                self.min = smin
            if self.max is None or smax > self.max:
                self.max = smax
            for i, n in buckets.items():
                if 0 <= i < len(self._buckets):
                    self._buckets[i] += n


class Window:
    """Sliding-time-window series: a bounded ring buffer of
    ``(t, value)`` samples answering "p50/p95/rate over the last N
    seconds" — the SLO view a process-lifetime histogram cannot give
    (an always-on server's lifetime p95 hides the last minute's
    regression).  Percentiles are EXACT over the in-window samples
    (nearest-rank), not bucket estimates; the ring bound
    (``maxlen``) caps memory, so under sustained load the window may
    effectively shrink below ``window_s`` — honest for an SLO view,
    which cares about the most recent samples anyway."""

    DEFAULT_WINDOW_S = 60.0

    __slots__ = ("name", "_lock", "_buf", "total", "_ex")

    def __init__(self, name, maxlen=4096):
        self.name = name
        self._lock = threading.Lock()
        self._buf = deque(maxlen=int(maxlen))  # raft-lint: guarded-by=self._lock
        self.total = 0  # lifetime count  # raft-lint: guarded-by=self._lock
        # exemplar'd samples (t, value, labels): bounded ring; pruned
        # to the window on read, ranked on demand by tail_exemplars()
        self._ex = deque(maxlen=256)  # raft-lint: guarded-by=self._lock

    def observe(self, v, t=None, exemplar=None):
        t = time.perf_counter() if t is None else float(t)
        with self._lock:
            self._buf.append((t, float(v)))
            self.total += 1
            if exemplar is not None:
                k, vmin = _exemplar_limits()
                if k > 0 and float(v) >= vmin:
                    self._ex.append((t, float(v), dict(exemplar)))

    def tail_exemplars(self, k=None, window_s=None, now=None):
        """The K largest exemplar'd in-window samples, worst first, as
        ``(value, labels)`` — "the actual p99 request of the last
        minute", live (the :class:`Histogram` exemplars answer the same
        question over the process lifetime).  Does NOT emit
        ``exemplar_recorded`` (the paired histogram observation already
        did; double events would double-join in ``report --tail``)."""
        window_s = self.DEFAULT_WINDOW_S if window_s is None else window_s
        now = time.perf_counter() if now is None else float(now)
        if k is None:
            k = _exemplar_limits()[0]
        with self._lock:
            live = [(v, labels) for t, v, labels in self._ex
                    if now - t <= window_s]
        return sorted(live, key=lambda e: -e[0])[:max(k, 0)]

    def values(self, window_s=None, now=None):
        """In-window sample values, oldest first."""
        window_s = self.DEFAULT_WINDOW_S if window_s is None else window_s
        now = time.perf_counter() if now is None else float(now)
        with self._lock:
            return [v for t, v in self._buf if now - t <= window_s]

    @staticmethod
    def _nearest_rank(sorted_vals, p):
        i = min(len(sorted_vals) - 1,
                max(0, round(p * (len(sorted_vals) - 1))))
        return sorted_vals[i]

    def percentile(self, p, window_s=None, now=None):
        """Exact nearest-rank p-quantile (0..1) over the window, or
        None when the window holds no samples."""
        vals = sorted(self.values(window_s, now))
        return self._nearest_rank(vals, p) if vals else None

    def snapshot(self, window_s=None, now=None):
        window_s = self.DEFAULT_WINDOW_S if window_s is None else window_s
        vals = sorted(self.values(window_s, now))
        if not vals:
            return {"count": 0, "window_s": window_s, "total": self.total}
        return {
            "count": len(vals),
            "window_s": window_s,
            "total": self.total,
            "rate_per_s": round(len(vals) / window_s, 4),
            "p50": round(self._nearest_rank(vals, 0.50), 6),
            "p95": round(self._nearest_rank(vals, 0.95), 6),
            "max": round(vals[-1], 6),
        }


_REGISTRY_LOCK = threading.Lock()
_REGISTRY: dict[str, object] = {}  # raft-lint: guarded-by=_REGISTRY_LOCK


def _get(name, cls):
    with _REGISTRY_LOCK:
        m = _REGISTRY.get(name)
        if m is None:
            m = _REGISTRY[name] = cls(name)
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, requested {cls.__name__}")
        return m


def counter(name) -> Counter:
    return _get(name, Counter)


def gauge(name) -> Gauge:
    return _get(name, Gauge)


def histogram(name) -> Histogram:
    return _get(name, Histogram)


def window(name) -> Window:
    return _get(name, Window)


def sample_windows(window_s=None):
    """``{name: snapshot}`` of every registered window — the heartbeat
    embeds this in each ``heartbeat`` event's ``windows`` payload so a
    capture shows the sliding p50/p95/rate view over time."""
    with _REGISTRY_LOCK:
        items = [(n, m) for n, m in sorted(_REGISTRY.items())
                 if isinstance(m, Window)]
    return {n: m.snapshot(window_s) for n, m in items}


def merge_states(states, name="merged"):
    """Pool several :meth:`Histogram.state` dicts into one fresh
    (unregistered) histogram — the fabric's fleet-wide ``shard_wall_s``
    view.  Returns the pooled :class:`Histogram` (query ``.count`` /
    ``.percentile``)."""
    h = Histogram(name)
    for s in states:
        if s:
            h.merge_state(s)
    return h


def reset():
    """Drop every registered metric (tests; also lets one process run
    independent sweeps with per-sweep snapshots)."""
    with _REGISTRY_LOCK:
        _REGISTRY.clear()


def snapshot():
    """JSON-ready snapshot of the whole registry, grouped by metric
    kind.  This is what lands in ``metrics.json``, the sweep manifest
    and the bench breakdown."""
    with _REGISTRY_LOCK:
        items = sorted(_REGISTRY.items())
    out = {"uptime_s": round(time.perf_counter() - _T0, 3),
           "counters": {}, "gauges": {}, "histograms": {}, "windows": {}}
    for name, m in items:
        kind = {Counter: "counters", Gauge: "gauges",
                Histogram: "histograms", Window: "windows"}[type(m)]
        out[kind][name] = m.snapshot()
    if not out["windows"]:
        del out["windows"]  # snapshot schema unchanged for non-serving
    return out


def _prom_name(name):
    return "raft_tpu_" + "".join(
        c if c.isalnum() or c == "_" else "_" for c in name)


def _escape_label(v):
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _exemplar_suffix(exemplar):
    """OpenMetrics exemplar clause for one bucket line:
    ``# {trace_id="..",span_id=".."} <value> <unix_ts>``."""
    v, unix_t, labels = exemplar
    body = ",".join(f'{k}="{_escape_label(val)}"'
                    for k, val in sorted(labels.items()))
    return f"# {{{body}}} {v:.6g} {unix_t:.3f}"


def to_prometheus():
    """Render the registry in the Prometheus text exposition format
    (counters/gauges as single samples, histograms as the standard
    ``_bucket``/``_sum``/``_count`` family, with OpenMetrics exemplar
    clauses on the buckets that hold one)."""
    with _REGISTRY_LOCK:
        items = sorted(_REGISTRY.items())
    lines = []
    for name, m in items:
        pn = _prom_name(name)
        if isinstance(m, Counter):
            lines.append(f"# TYPE {pn} counter")
            lines.append(f"{pn} {m.value}")
        elif isinstance(m, Gauge):
            if m.value is None:
                continue
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {m.value}")
            lines.append(f"{pn}_max {m.max}")
        elif isinstance(m, Window):
            snap = m.snapshot()
            if not snap["count"]:
                continue
            lines.append(f"# TYPE {pn} gauge")
            for k in ("p50", "p95", "max", "count", "rate_per_s"):
                lines.append(f"{pn}_{k} {snap[k]}")
        else:
            lines.append(f"# TYPE {pn} histogram")
            last_nonzero = 0
            pairs = m.buckets()
            ex = m.exemplars()
            for i, (_, acc) in enumerate(pairs):
                if acc != (pairs[i - 1][1] if i else 0):
                    last_nonzero = i
            for i, (bound, acc) in enumerate(pairs[: last_nonzero + 1]):
                line = f'{pn}_bucket{{le="{bound:.6g}"}} {acc}'
                if i in ex:
                    line += f" {_exemplar_suffix(ex[i])}"
                lines.append(line)
            line = f'{pn}_bucket{{le="+Inf"}} {m.count}'
            if len(BUCKET_BOUNDS) in ex:  # overflow-bucket exemplar
                line += f" {_exemplar_suffix(ex[len(BUCKET_BOUNDS)])}"
            lines.append(line)
            lines.append(f"{pn}_sum {m.sum}")
            lines.append(f"{pn}_count {m.count}")
    return "\n".join(lines) + "\n"


def export(path):
    """Write :func:`to_prometheus` to ``path`` (best-effort: exporting
    metrics must never take down the run that produced them).

    Atomic tmp + ``os.replace``: the export path is re-written at every
    sweep_done / serve drain while scrapers and ``obs runs`` readers
    may be mid-read — a plain truncate-and-write would hand them half
    an exposition (the ``atomic-write`` concurrency-lint class)."""
    import os
    import tempfile

    try:
        d = os.path.dirname(os.path.abspath(path))
        fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(to_prometheus())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return True
    except OSError:
        return False
