"""Seeded negatives for the ``lock-discipline`` concurrency rule."""

import threading

_LOCK = threading.Lock()
REGISTRY = {}  # raft-lint: guarded-by=_LOCK


def register_ok(name, value):
    with _LOCK:
        REGISTRY[name] = value


def register_bad(name, value):
    REGISTRY[name] = value      # item write outside the lock
    REGISTRY.pop(name, None)    # mutating method outside the lock


def snapshot():
    return dict(REGISTRY)       # reads are not gated


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}  # raft-lint: guarded-by=self._lock
        self._bytes = 0  # raft-lint: guarded-by=self._lock

    def put_ok(self, k, v):
        with self._lock:
            self._items[k] = v
            self._bytes += 1

    def put_bad(self, k, v):
        self._items[k] = v      # instance state outside its lock
        self._bytes += 1        # augmented assign outside its lock
