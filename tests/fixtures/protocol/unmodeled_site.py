"""Seeded protocol drift: an unmodeled mutation in a new module.

A hypothetical sidecar that "compacts" worker status records by
renaming them with raw ``os.rename`` — bypassing the fsops seam, so
the interleaving explorer can never crash or reorder it.  The static
extraction pass must flag the raw call as protocol-unmodeled (and the
sanctioned write below as a site the baseline has never seen).

This fixture is SCANNED, never imported: ``PROTOCOL_MODULE`` tells the
static engine to treat this file as that protocol module and diff it
against the pinned baseline.  ``python -m raft_tpu.analysis protocol
check --fixture <this file>`` must exit 1.
"""

import json
import os

from raft_tpu.utils import fsops

PROTOCOL_MODULE = "sidecar"


def compact_status(status_dir, records):
    merged = os.path.join(status_dir, "status.json")
    fsops.write_atomic(merged, json.dumps(records))
    for name in sorted(records):
        # raw rename: invisible to the model checker
        os.rename(os.path.join(status_dir, name + ".json"),
                  os.path.join(status_dir, name + ".done"))
