"""Frequency-domain lumped-mass mooring line dynamics (moorMod 1/2).

The reference delegates dynamic mooring to MoorPy's lumped-mass
frequency-domain solver (``line.dynamicSolve`` /
``getCoupledDynamicMatrices``, consumed at
``/root/reference/raft/raft_model.py:379-404``,
``raft_fowt.py:2281-2289``, ``helpers.py:786``).  Here the same physics
is built TPU-first:

* the line is discretised into lumped nodes along its *static elastic
  catenary* profile (positions + mean tensions from the same closed
  forms as the quasi-static module);
* per-node 3-DOF equations carry structural + added mass, axial EA and
  geometric (mean-tension) stiffness, stochastically linearised Morison
  drag, and wave-kinematics excitation;
* the boundary nodes move with the platform fairlead RAO (anchor end
  fixed); grounded nodes are vertically supported by the seabed;
* the interior system solves as ONE batched complex solve over the
  frequency axis — ``jnp.linalg.solve`` on (nw, n_int, n_int) — with
  the drag linearisation as a small fixed-point loop, exactly the
  pattern of the platform dynamics kernel.

Outputs: dynamic tension amplitudes along the line (the moorMod 1
tension post-processing) and the condensed fairlead impedance Z(w)
(3x3 per frequency) whose real/imag parts are the moorMod 2 dynamic
mooring stiffness/damping felt by the platform.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.ops import waves as wv
from raft_tpu.physics.mooring import solve_catenary
from raft_tpu.utils.dtypes import compute_dtypes


def line_static_shape(r_anchor, r_fair, L, w_lin, EA, n_seg=24,
                      can_ground=True):
    """Node positions and mean tensions along the static elastic
    catenary (anchor = node 0, fairlead = node n_seg).

    Returns (r_nodes (n+1,3), T_nodes (n+1,), grounded (n+1,) bool).
    """
    r_anchor = np.asarray(r_anchor, dtype=float)
    r_fair = np.asarray(r_fair, dtype=float)
    dv = r_fair - r_anchor
    XF = float(np.hypot(dv[0], dv[1]))
    ZF = float(dv[2])
    uh = dv[:2] / max(XF, 1e-9)

    HF, VF, _, _ = solve_catenary(XF, ZF, L, w_lin, EA, can_ground=can_ground)
    HF, VF = float(HF), float(VF)

    VA = VF - w_lin * L
    LB = max(L - VF / w_lin, 0.0) if (can_ground and VF < w_lin * L) else 0.0
    if LB > 0:
        # non-uniform nodes: touchdown is a node; most resolution goes
        # to the suspended span (the grounded part is straight)
        n_g = max(2, n_seg // 6)
        n_s = n_seg - n_g
        s = np.concatenate([np.linspace(0.0, LB, n_g + 1)[:-1],
                            np.linspace(LB, L, n_s + 1)])
    else:
        s = np.linspace(0.0, L, n_seg + 1)
    grounded = s <= LB + 1e-9

    x = np.zeros_like(s)
    z = np.zeros_like(s)
    T = np.zeros_like(s)
    for i, si in enumerate(s):
        if can_ground and VF < w_lin * L:   # partly grounded
            if si <= LB:
                x[i] = si * (1.0 + HF / EA)
                z[i] = 0.0
                T[i] = HF
            else:
                sp = si - LB
                V = w_lin * sp
                x[i] = (LB * (1.0 + HF / EA)
                        + (HF / w_lin) * np.arcsinh(V / HF) + HF * sp / EA)
                z[i] = ((HF / w_lin) * (np.sqrt(1 + (V / HF) ** 2) - 1.0)
                        + V**2 / (2 * EA * w_lin))
                T[i] = np.hypot(HF, V)
        else:                                # fully suspended
            V = VA + w_lin * si
            x[i] = ((HF / w_lin) * (np.arcsinh(V / HF) - np.arcsinh(VA / HF))
                    + HF * si / EA)
            z[i] = ((HF / w_lin) * (np.sqrt(1 + (V / HF) ** 2)
                                    - np.sqrt(1 + (VA / HF) ** 2))
                    + (VA * si + 0.5 * w_lin * si**2) / EA)
            T[i] = np.hypot(HF, V)

    r_nodes = np.zeros((n_seg + 1, 3))
    r_nodes[:, 0] = r_anchor[0] + x * uh[0]
    r_nodes[:, 1] = r_anchor[1] + x * uh[1]
    r_nodes[:, 2] = r_anchor[2] + z
    return r_nodes, T, grounded, s


def line_dynamics(r_nodes, T_nodes, grounded, L, EA, m_lin, d_vol,
                  w_arr, k_arr, zeta, beta, depth, rho=1025.0, g=9.81,
                  Cd=1.2, Ca=1.0, CdAx=0.05, CaAx=0.0,
                  RAO_A=None, RAO_B=None, n_drag_iter=5, s_arc=None,
                  BA=0.0):
    """Frequency-domain lumped-mass solve for one line.

    r_nodes/T_nodes/grounded/s_arc : static discretisation from
    :func:`line_static_shape` (n+1 nodes; s_arc = unstretched arc
    coordinates, uniform L/n when omitted).
    zeta : (nw,) complex wave component amplitudes; beta heading [rad].
    RAO_A/RAO_B : (3, nw) complex end-motion amplitudes (None = fixed).

    Returns dict with
      T_amp   : (n+1, nw) dynamic tension amplitudes,
      Z_fair  : (nw, 3, 3) condensed impedance at end B,
      X       : (n-1, 3, nw) interior node motion amplitudes.
    """
    r_nodes = np.asarray(r_nodes)
    n = len(r_nodes) - 1          # segments
    n_int = n - 1                 # interior nodes
    nw = len(w_arr)
    w_arr = jnp.asarray(w_arr)
    if s_arc is None:
        l0 = np.full(n, L / n)
    else:
        l0 = np.diff(np.asarray(s_arc, dtype=float))
    l0 = np.maximum(l0, 1e-9)
    ds_node = np.zeros(n + 1)
    ds_node[:-1] += 0.5 * l0
    ds_node[1:] += 0.5 * l0

    seg_vec = r_nodes[1:] - r_nodes[:-1]
    l_seg = np.linalg.norm(seg_vec, axis=1)
    t_seg = seg_vec / np.maximum(l_seg, 1e-9)[:, None]
    T_seg = 0.5 * (T_nodes[1:] + T_nodes[:-1])

    A_c = np.pi / 4 * d_vol**2

    # ---- per-segment 3x3 stiffness: axial EA + geometric tension
    tt = np.einsum("si,sj->sij", t_seg, t_seg)
    I3 = np.eye(3)
    k_seg = ((EA / l0)[:, None, None] * tt
             + (T_seg / np.maximum(l_seg, 1e-9))[:, None, None] * (I3 - tt))

    # ---- internal (structural) axial damping per segment, MoorDyn BA
    # convention: BA >= 0 is the damping coefficient [N-s] (force =
    # BA * strain rate -> c = BA / l0); BA < 0 means |BA| is a ratio of
    # critical damping, realised here as the segment spring-mass
    # critical damping 2 sqrt(k m) (MoorDyn's exact per-segment
    # constant is not verifiable in this image — MoorPy/MoorDyn sources
    # absent; a factor-level difference only shifts the already
    # heavily-damped axial mode)
    if BA < 0:
        c_ax = -BA * 2.0 * np.sqrt((EA / l0) * (m_lin * l0))
    else:
        c_ax = np.full(n, BA) / l0
    c_seg = c_ax[:, None, None] * tt

    # ---- assemble interior stiffness/damping and end-coupling blocks
    K = np.zeros((3 * n_int, 3 * n_int))
    K_A = np.zeros((3 * n_int, 3))   # coupling to node 0 (anchor end)
    K_B = np.zeros((3 * n_int, 3))   # coupling to node n (fairlead end)
    C = np.zeros((3 * n_int, 3 * n_int))
    C_A = np.zeros((3 * n_int, 3))
    C_B = np.zeros((3 * n_int, 3))
    for si in range(n):
        iL, iR = si - 1, si          # interior indices of segment ends
        for mat, matA, matB, k in ((K, K_A, K_B, k_seg[si]),
                                   (C, C_A, C_B, c_seg[si])):
            if 0 <= iL < n_int:
                mat[3 * iL:3 * iL + 3, 3 * iL:3 * iL + 3] += k
            if 0 <= iR < n_int:
                mat[3 * iR:3 * iR + 3, 3 * iR:3 * iR + 3] += k
            if 0 <= iL < n_int and 0 <= iR < n_int:
                mat[3 * iL:3 * iL + 3, 3 * iR:3 * iR + 3] -= k
                mat[3 * iR:3 * iR + 3, 3 * iL:3 * iL + 3] -= k
            if iL == -1 and 0 <= iR < n_int:
                matA[3 * iR:3 * iR + 3] -= k
            if iR == n - 1 and 0 <= iL < n_int:
                matB[3 * iL:3 * iL + 3] -= k

    # ---- nodal mass + added mass (node tangent = mean of segments)
    t_node = np.zeros((n + 1, 3))
    t_node[0] = t_seg[0]
    t_node[-1] = t_seg[-1]
    t_node[1:-1] = t_seg[:-1] + t_seg[1:]
    t_node /= np.maximum(np.linalg.norm(t_node, axis=1), 1e-9)[:, None]
    ttn = np.einsum("ni,nj->nij", t_node, t_node)
    M_node = (m_lin * ds_node[:, None, None] * I3[None]
              + rho * A_c * ds_node[:, None, None]
              * (Ca * (I3[None] - ttn) + CaAx * ttn))

    M = np.zeros((3 * n_int, 3 * n_int))
    for i in range(n_int):
        M[3 * i:3 * i + 3, 3 * i:3 * i + 3] = M_node[i + 1]

    # seabed support: grounded interior nodes are vertically clamped
    # (unilateral contact linearised about the resting state)
    clamp = np.zeros(3 * n_int, dtype=bool)
    for i in range(n_int):
        if grounded[i + 1]:
            clamp[3 * i + 2] = True

    # ---- wave kinematics at the nodes
    # complex width follows the inputs (f32 pipelines stay complex64)
    cdt = compute_dtypes(w_arr, zeta)[1]
    zeta = jnp.asarray(zeta).astype(cdt)
    u, ud, _ = wv.wave_kinematics(
        zeta[None, :], beta, w_arr, jnp.asarray(k_arr), depth,
        jnp.asarray(r_nodes), rho=rho, g=g)   # (n+1, 3, nw)

    # end-motion amplitudes
    XA = jnp.zeros((3, nw), dtype=cdt) if RAO_A is None else jnp.asarray(RAO_A)
    XB = jnp.zeros((3, nw), dtype=cdt) if RAO_B is None else jnp.asarray(RAO_B)

    K_j = jnp.asarray(K)
    M_j = jnp.asarray(M)
    C_j = jnp.asarray(C)
    K_A_j = jnp.asarray(K_A)
    K_B_j = jnp.asarray(K_B)
    C_A_j = jnp.asarray(C_A)
    C_B_j = jnp.asarray(C_B)
    clamp_j = jnp.asarray(clamp)

    # Morison inertial excitation on interior nodes
    F_in = (rho * A_c * jnp.asarray(ds_node[1:-1])[:, None, None]) * (
        (1.0 + Ca) * (ud[1:-1] - jnp.einsum("nij,njw->niw", ttn[1:-1], ud[1:-1]))
        + (1.0 + CaAx) * jnp.einsum("nij,njw->niw", ttn[1:-1], ud[1:-1])
    )  # (n_int, 3, nw)

    drag_c = 0.5 * rho * d_vol * jnp.asarray(ds_node[1:-1])

    # scatter indices for block-diagonal placement of (n_int, 3, 3)
    _bi = 3 * np.arange(n_int)[:, None, None]
    _rows = jnp.asarray(_bi + np.arange(3)[None, :, None] + np.zeros((1, 1, 3), int))
    _cols = jnp.asarray(_bi + np.zeros((1, 3, 1), int) + np.arange(3)[None, None, :])

    def block_diag(Bn):
        return jnp.zeros((3 * n_int, 3 * n_int)).at[_rows, _cols].set(Bn)

    def solve_with_B(Bn):
        """Assemble+solve given per-node 3x3 drag matrices (n_int,3,3)."""
        Bfull = block_diag(Bn)
        F_drag = jnp.einsum("nij,njw->niw", Bn, u[1:-1])
        F = (F_in + F_drag).transpose(2, 0, 1).reshape(nw, 3 * n_int)
        iwc = 1j * w_arr[:, None]
        F = (F - jnp.einsum("ij,jw->wi", K_A_j, XA)
             - jnp.einsum("ij,jw->wi", K_B_j, XB)
             - iwc * jnp.einsum("ij,jw->wi", C_A_j, XA)
             - iwc * jnp.einsum("ij,jw->wi", C_B_j, XB))
        D = (K_j[None] + 1j * w_arr[:, None, None] * (Bfull + C_j)[None]
             - (w_arr**2)[:, None, None] * M_j[None])
        D = D.astype(cdt)
        # clamped dofs: identity rows/cols, zero rhs
        idx = jnp.where(clamp_j, 1.0, 0.0)
        D = D * (1 - idx[None, :, None]) * (1 - idx[None, None, :])
        D = D + jnp.eye(3 * n_int)[None] * idx[None, :]
        F = F * (1 - idx[None, :])
        X = jnp.linalg.solve(D, F[..., None])[..., 0]   # (nw, 3 n_int)
        return X

    Bn = jnp.zeros((n_int, 3, 3))
    X = solve_with_B(Bn)
    for _ in range(n_drag_iter):
        Xn = X.reshape(nw, n_int, 3).transpose(1, 2, 0)   # (n_int, 3, nw)
        v_node = 1j * w_arr[None, None, :] * Xn
        vrel = u[1:-1] - v_node
        # RMS per node per direction split transverse/axial
        vt = jnp.einsum("nij,njw->niw", ttn[1:-1], vrel)
        vp = vrel - vt
        sig_p = jnp.sqrt(0.5 * jnp.sum(jnp.abs(vp) ** 2, axis=(1, 2)))
        sig_t = jnp.sqrt(0.5 * jnp.sum(jnp.abs(vt) ** 2, axis=(1, 2)))
        cfac = jnp.sqrt(8.0 / jnp.pi) * drag_c
        Bn = (cfac * Cd * sig_p)[:, None, None] * (I3[None] - ttn[1:-1]) \
            + (cfac * CdAx * sig_t)[:, None, None] * ttn[1:-1]
        X = solve_with_B(Bn)

    # ---- dynamic tensions: axial stretch per segment
    Xn = X.reshape(nw, n_int, 3).transpose(1, 2, 0)       # (n_int, 3, nw)
    X_all = jnp.concatenate([XA[None], Xn, XB[None]], axis=0)  # (n+1,3,nw)
    dX = X_all[1:] - X_all[:-1]
    # axial tension incl. the internal-damping contribution
    # T = EA*strain + c_ax*l0*strain_rate
    T_amp_seg = (jnp.asarray(EA / l0)[:, None]
                 + 1j * w_arr[None, :] * jnp.asarray(c_ax)[:, None]) * \
        jnp.einsum("si,siw->sw", jnp.asarray(t_seg), dX)
    T_amp = jnp.concatenate([
        T_amp_seg[:1], 0.5 * (T_amp_seg[1:] + T_amp_seg[:-1]), T_amp_seg[-1:]
    ], axis=0)  # (n+1, nw)

    # ---- condensed fairlead impedance Z(w): force at end B per unit
    # end-B motion with the interior dynamically condensed out
    Bfull = block_diag(Bn)
    D = (K_j[None] + 1j * w_arr[:, None, None] * (Bfull + C_j)[None]
         - (w_arr**2)[:, None, None] * M_j[None])
    D = D.astype(cdt)
    idx = jnp.where(clamp_j, 1.0, 0.0)
    D = D * (1 - idx[None, :, None]) * (1 - idx[None, None, :])
    D = D + jnp.eye(3 * n_int)[None] * idx[None, :]
    # frequency-dependent end coupling incl. structural damping
    KC_B = (K_B_j[None] + 1j * w_arr[:, None, None] * C_B_j[None]) \
        * (1 - idx[None, :, None])
    # K_bb at the fairlead: last segment stiffness/damping (+ half node mass)
    K_bb = jnp.asarray(k_seg[-1])
    C_bb = jnp.asarray(c_seg[-1])
    M_bb = jnp.asarray(M_node[-1]) * 0.5
    Dinv_KB = jnp.linalg.solve(D, KC_B)
    Z_fair = (K_bb[None] + 1j * w_arr[:, None, None] * C_bb[None]
              - (w_arr**2)[:, None, None] * M_bb[None]
              - jnp.einsum("wij,wjk->wik", jnp.swapaxes(KC_B, 1, 2), Dinv_KB))
    return dict(T_amp=T_amp, Z_fair=Z_fair, X=Xn)


def fowt_line_tension_amps(ms, r6, Xi_PRP, w_arr, k_arr, S, beta, depth,
                           rho=1025.0, g=9.81, n_seg=24):
    """Dynamic end-tension amplitudes for every line of a FOWT's
    quasi-static MooringSystem under platform motion Xi (moorMod 1
    tension post-processing; raft_fowt.py:2373-2387).

    Xi_PRP : (6, nw) platform motion amplitudes for one excitation
    source.  Returns (2*nL, nw): [end A..., end B...] amplitudes.
    """
    from raft_tpu.ops.transforms import rotation_matrix

    w_np = np.asarray(w_arr)
    nw = len(w_np)
    nL = ms.n_lines
    dw = w_np[1] - w_np[0]
    zeta = np.sqrt(2 * np.asarray(S) * dw).astype(np.complex128)
    out = np.zeros((2 * nL, nw), dtype=np.complex128)

    R = np.asarray(rotation_matrix(r6[3], r6[4], r6[5]))
    Xi_j = jnp.asarray(Xi_PRP)
    for il in range(nL):
        r_fair = np.asarray(r6[:3]) + R @ np.asarray(ms.r_fair0[il])
        # fairlead motion amplitudes from the platform RAO
        lever = jnp.asarray(r_fair - np.asarray(r6[:3]))
        dr, _, _ = wv.get_kinematics(lever, Xi_j, jnp.asarray(w_np))
        r_nodes, T_nodes, grounded, s_arc = line_static_shape(
            ms.r_anchor[il], r_fair, float(ms.L[il]), float(ms.w[il]),
            float(ms.EA[il]), n_seg=n_seg)
        res = line_dynamics(
            r_nodes, T_nodes, grounded, float(ms.L[il]), float(ms.EA[il]),
            float(ms.m_lin[il]), float(ms.d_vol[il]),
            w_np, np.asarray(k_arr), zeta, float(beta), depth, rho=rho, g=g,
            Cd=float(ms.Cd[il]), Ca=float(ms.Ca[il]),
            CdAx=float(ms.CdAx[il]), CaAx=float(ms.CaAx[il]),
            BA=float(ms.BA[il]) if ms.BA is not None else 0.0,
            RAO_A=None, RAO_B=np.asarray(dr), s_arc=s_arc)
        out[il] = np.asarray(res["T_amp"][0])
        out[il + nL] = np.asarray(res["T_amp"][-1])
    return out


def fowt_mooring_impedance(ms, r6, w_arr, k_arr, S, beta, depth,
                           rho=1025.0, g=9.81, n_seg=24):
    """Frequency-dependent 6x6 mooring impedance about the platform
    reference (moorMod 2: replaces the quasi-static C_moor in the
    platform impedance; raft_model.py:1020-1031).

    Returns Z_moor (nw, 6, 6) complex."""
    from raft_tpu.ops.transforms import rotation_matrix, skew

    w_np = np.asarray(w_arr)
    nw = len(w_np)
    dw = w_np[1] - w_np[0]
    zeta = np.sqrt(2 * np.asarray(S) * dw).astype(np.complex128)
    R = np.asarray(rotation_matrix(r6[3], r6[4], r6[5]))
    Z = jnp.zeros((nw, 6, 6), dtype=compute_dtypes()[1])
    for il in range(ms.n_lines):
        r_fair = np.asarray(r6[:3]) + R @ np.asarray(ms.r_fair0[il])
        r_nodes, T_nodes, grounded, s_arc = line_static_shape(
            ms.r_anchor[il], r_fair, float(ms.L[il]), float(ms.w[il]),
            float(ms.EA[il]), n_seg=n_seg)
        res = line_dynamics(
            r_nodes, T_nodes, grounded, float(ms.L[il]), float(ms.EA[il]),
            float(ms.m_lin[il]), float(ms.d_vol[il]),
            w_np, np.asarray(k_arr), zeta, float(beta), depth, rho=rho, g=g,
            Cd=float(ms.Cd[il]), Ca=float(ms.Ca[il]),
            CdAx=float(ms.CdAx[il]), CaAx=float(ms.CaAx[il]),
            BA=float(ms.BA[il]) if ms.BA is not None else 0.0, s_arc=s_arc)
        Zf = res["Z_fair"]                       # (nw, 3, 3)
        lever = jnp.asarray(r_fair - np.asarray(r6[:3]))
        H = skew(lever)                          # Hv = cross(v, lever)
        # 6x6 from a 3x3 at the fairlead: translate like a mass matrix
        Ht = H.T
        Z = Z.at[:, :3, :3].add(Zf)
        Z = Z.at[:, :3, 3:].add(Zf @ H)
        Z = Z.at[:, 3:, :3].add(jnp.einsum("ij,wjk->wik", Ht, Zf))
        Z = Z.at[:, 3:, 3:].add(jnp.einsum("ij,wjk,kl->wil", Ht, Zf, H))
    return Z
