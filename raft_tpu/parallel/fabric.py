"""Elastic multi-worker sweep fabric: lease ledger, workers, stealing.

Everything RAFT_TPU ran before this module was ONE Python process
walking shards serially (:func:`raft_tpu.parallel.resilience.
run_checkpointed`).  Here the shard queue becomes a shared **work
ledger** living in the sweep's ``out_dir`` — no server, no locks
beyond the filesystem — and any number of **worker processes**, on one
host or many, drain it concurrently:

* **ledger** — per-shard lease records under ``<out_dir>/_fabric/``
  written with the same atomic patterns the checkpoint layer already
  trusts: a *claim* is ``O_CREAT|O_EXCL`` on the lease file (exactly
  one claimant wins), a *renewal* is an atomic tmp+``os.replace``
  rewrite, a *steal* is an ``os.rename`` of the expired lease away
  (exactly one stealer wins the rename);
* **workers** (``python -m raft_tpu.parallel.fabric worker``) loop:
  claim an unleased/expired shard, evaluate it through the SAME
  retry/OOM-halving/quarantine/escalation path as the serial runner
  (:func:`~raft_tpu.parallel.resilience.evaluate_shard`), write the
  shard atomically, release the lease.  A worker that dies mid-shard
  simply stops renewing; its lease expires and the shard is
  re-claimed — the PR-1 corrupt/truncated-shard detection makes the
  half-written ``.npz`` safe to requeue, and re-execution is
  deterministic so double-computation (live straggler stolen from) is
  benign;
* **work stealing** — a lease is stealable when it EXPIRED (holder
  stopped renewing: dead or wedged), when the holder's status-file
  heartbeat went stale, or when its age exceeds
  ``RAFT_TPU_FABRIC_STEAL_MULT`` x the fleet-pooled ``shard_wall_s``
  p95 (bucket counts from every worker's status file merge exactly —
  :func:`raft_tpu.obs.metrics.merge_states`) — stragglers never gate
  sweep completion;
* **coordinator** (``fabric run --workers N`` /
  :func:`run_fabric`) initializes the ledger, spawns N local worker
  subprocesses, waits on the ledger and assembles results exactly as
  the serial runner does (manifest statuses, merged quarantine.json,
  metrics.json) — callers see the same out_dir layout and the same
  concatenated result dict, bit-identical to a serial run.

Workers rebuild their evaluator from an importable **entry spec**
(``module:callable`` or ``path.py:callable`` — never a pickled
closure); the callable returns the shard ``compute(chunk, mesh)``
(usually via :func:`raft_tpu.parallel.sweep.full_compute` /
``case_compute``) or a dict ``{"compute", "cases", "warmup"}``.
Evaluator factories advertise their entry by stamping
``evaluate._raft_fabric_entry = {"entry": "mod:fn", "kwargs": {...}}``;
with that stamp in place, ``RAFT_TPU_FABRIC_WORKERS=N`` routes any
checkpointed sweep (``sweep_10k.py`` included) through the fabric with
zero caller changes.

Cold start: an entry can name an AOT warmup spec — workers push it
through :func:`raft_tpu.aot.warmup.warmup_model` before their first
claim, so a worker joining mid-sweep on a warmed bank
(``RAFT_TPU_AOT=load``/``require``) answers its first shard without
the 25s+ trace/compile tax and reports ``programs_compiled=0`` on its
``fabric_worker_start`` event.

Multi-host: ``RAFT_TPU_DIST*`` + :func:`raft_tpu.parallel.sweep.
ensure_distributed` build one global mesh per worker across hosts;
the ledger needs nothing new — a shared filesystem is the only
requirement (the same one the checkpoint shards already have).

Failure injection (:mod:`raft_tpu.utils.faults`): ``worker_kill:
worker_shard`` SIGKILLs a worker right after it claims a lease;
``lease_expire:lease_renew`` makes a worker silently stop renewing.
The coordinator forwards these two kinds to exactly ONE worker
(``RAFT_TPU_FABRIC_FAULT_WORKER``) so the kill-a-worker acceptance
test is deterministic.
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import uuid

import numpy as np

from raft_tpu.obs import metrics
from raft_tpu.obs.heartbeat import maybe_heartbeat
from raft_tpu.obs.spans import ambient_ids, propagation_env, span
from raft_tpu.parallel import resilience
from raft_tpu.utils import config, faults, fsops
from raft_tpu.utils.structlog import log_event

FABRIC_DIRNAME = "_fabric"
SPEC_NAME = "fabric.json"
CASES_NAME = "cases.npz"

#: observations required before the pooled shard_wall_s p95 is trusted
#: to judge stragglers (below this, only TTL expiry steals)
MIN_WALL_SAMPLES = 4


class FabricError(RuntimeError):
    """The fabric could not complete the sweep (all workers died with
    shards remaining, or assembly found a missing/corrupt shard)."""


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


# ------------------------------------------------------------------ paths


def fabric_dir(out_dir):
    return os.path.join(out_dir, FABRIC_DIRNAME)


def _spec_path(out_dir):
    return os.path.join(fabric_dir(out_dir), SPEC_NAME)


def _cases_path(out_dir):
    return os.path.join(fabric_dir(out_dir), CASES_NAME)


def _lease_path(out_dir, shard):
    return os.path.join(fabric_dir(out_dir), "leases",
                        f"shard_{shard:04d}.json")


def _done_path(out_dir, shard):
    return os.path.join(fabric_dir(out_dir), "done",
                        f"shard_{shard:04d}.json")


def _workers_dir(out_dir):
    return os.path.join(fabric_dir(out_dir), "workers")


def _worker_path(out_dir, worker_id):
    return os.path.join(_workers_dir(out_dir), f"{worker_id}.json")


def _shard_path(out_dir, shard):
    return os.path.join(out_dir, f"shard_{shard:04d}.npz")


def load_spec(out_dir):
    with open(_spec_path(out_dir)) as f:
        return json.load(f)


def load_cases(out_dir):
    with np.load(_cases_path(out_dir), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


# ------------------------------------------------- atomic lease primitives
#
# The three filesystem idioms every RAFT_TPU lease ledger is built
# from, factored out so other ledgers (the serving fleet's replica
# membership in :mod:`raft_tpu.serve.fleet`) reuse the EXACT semantics
# the sweep fabric trusts instead of re-deriving them: claim =
# ``O_CREAT|O_EXCL`` (exactly one creator), rewrite = tmp +
# ``os.replace`` (readers see old-or-new, never torn), steal/evict =
# ``os.rename`` to a unique grave (exactly one winner).


def lease_claim(path, rec):
    """Exclusive lease creation: True when THIS caller won the
    ``O_CREAT|O_EXCL`` race and wrote ``rec``."""
    try:
        fsops.create_exclusive(path, json.dumps(rec))
    except FileExistsError:
        return False
    return True


def lease_read(path):
    """``(record, mtime)`` of a lease file, or ``(None, None)`` when
    absent.  A present-but-unreadable lease (claimant mid-write) reads
    as an empty record with the file's mtime."""
    try:
        mtime = fsops.getmtime(path)
    except OSError:
        return None, None
    try:
        return json.loads(fsops.read_text(path)), mtime
    except (OSError, ValueError):
        return {}, mtime


def lease_rewrite(path, rec):
    """Atomic full rewrite of a lease record (renewals): tmp write +
    ``replace``, through the :mod:`~raft_tpu.utils.fsops` seam so the
    protocol checker can crash an actor between the two halves."""
    fsops.write_atomic(path, json.dumps(rec))


def lease_remove(path):
    """Atomically remove a lease via rename to a unique grave: True
    when THIS caller won the rename (steal/evict — the losing racer
    sees False and must not double-count the removal)."""
    grave = fsops.grave_name(path, "stolen")
    try:
        fsops.rename(path, grave)
    except OSError:
        return False
    try:
        fsops.unlink(grave)
    except OSError:
        pass
    return True


# ----------------------------------------------------------------- ledger


class Ledger:
    """The shared shard ledger for one sweep directory.

    Every mutation is a single atomic filesystem operation, so any
    number of processes (local or cross-host on a shared filesystem)
    can use one instance's worth of methods concurrently:

    * :meth:`claim` — ``O_CREAT|O_EXCL`` lease-file creation;
    * :meth:`renew` — atomic rewrite bumping ``renewed_t`` (ownership
      checked by token; a lost race recreates a lease the owner still
      legitimately holds — worst case two workers compute the same
      deterministic shard, which is benign);
    * :meth:`steal` — ``os.rename`` of the stealable lease to a
      unique grave name: exactly one stealer wins, the shard returns
      to the unleased pool;
    * :meth:`write_done` — atomic completion record (the shard
      ``.npz`` itself is the source of truth; the done record carries
      worker/wall/attempt/quarantine bookkeeping and spares rescans
      from re-validating every file).
    """

    def __init__(self, out_dir, n_shards, worker_id=None):
        self.out_dir = out_dir
        self.n_shards = int(n_shards)
        self.worker_id = worker_id
        self.token = uuid.uuid4().hex
        for sub in ("leases", "done", "workers"):
            fsops.makedirs(os.path.join(fabric_dir(out_dir), sub),
                           exist_ok=True)

    # -- leases

    def read_lease(self, shard):
        """``(record, mtime)`` of the shard's lease, or ``(None,
        None)``.  A present-but-unreadable lease (claimant mid-write)
        reads as an empty record with the file's mtime."""
        return lease_read(_lease_path(self.out_dir, shard))

    def claim(self, shard, attempt=1):
        """Try to claim the shard; True when THIS caller won the
        exclusive lease-file creation."""
        path = _lease_path(self.out_dir, shard)
        now = time.time()
        rec = {
            "worker": self.worker_id,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "claimed_t": now,
            "renewed_t": now,
            "ttl_s": float(config.get("FABRIC_TTL_S")),
            "attempt": int(attempt),
            "token": self.token,
        }
        ids = ambient_ids()  # active span or env-inherited trace ctx
        if ids is not None:
            rec["trace_id"], rec["parent_span_id"] = ids
        if not lease_claim(path, rec):
            return False
        metrics.counter("shards_claimed").inc()
        log_event("shard_claim", shard=shard, worker=self.worker_id,
                  attempt=int(attempt))
        return True

    def renew(self, shard):
        """Refresh the lease's ``renewed_t``; False when the lease is
        no longer this worker's (stolen or released)."""
        rec, _ = self.read_lease(shard)
        if not rec or rec.get("token") != self.token:
            return False
        rec["renewed_t"] = time.time()
        lease_rewrite(_lease_path(self.out_dir, shard), rec)
        return True

    def release(self, shard):
        """Drop this worker's lease (no-op when it was stolen)."""
        rec, _ = self.read_lease(shard)
        if rec and rec.get("token") == self.token:
            try:
                fsops.unlink(_lease_path(self.out_dir, shard))
            except OSError:
                pass

    def stealable(self, shard, now=None, pooled=None):
        """``(reason, age_s, holder, attempt)`` when the shard's lease
        may be stolen, else ``(None, ...)``.

        Reasons: ``expired`` (not renewed within TTL — a dead worker
        IS an expired lease), ``holder_stale`` (the holder's status
        file stopped updating), ``straggler`` (lease age exceeds
        ``RAFT_TPU_FABRIC_STEAL_MULT`` x the fleet-pooled
        ``shard_wall_s`` p95 with at least ``MIN_WALL_SAMPLES``
        observations).  Pass a precomputed ``pooled`` histogram when
        checking many shards in one pass — re-reading every worker
        status file per shard is pure polling I/O."""
        rec, mtime = self.read_lease(shard)
        if rec is None:
            return None, 0.0, None, 0
        now = time.time() if now is None else now
        ttl = float(rec.get("ttl_s") or config.get("FABRIC_TTL_S"))
        holder = rec.get("worker")
        attempt = int(rec.get("attempt") or 1)
        renewed = float(rec.get("renewed_t") or mtime)
        age = now - renewed
        if age > ttl:
            return "expired", age, holder, attempt
        if holder:
            try:
                st_m = fsops.getmtime(_worker_path(self.out_dir, holder))
                if now - st_m > ttl:
                    return "holder_stale", now - st_m, holder, attempt
            except OSError:
                pass  # holder never wrote a status file: TTL rules it
        claim_age = now - float(rec.get("claimed_t") or mtime)
        if pooled is None:
            pooled = self.pooled_walls()
        if pooled.count >= MIN_WALL_SAMPLES:
            p95 = pooled.percentile(0.95)
            mult = float(config.get("FABRIC_STEAL_MULT"))
            if p95 and p95 > 0 and claim_age > mult * p95:
                return "straggler", claim_age, holder, attempt
        return None, age, holder, attempt

    def steal(self, shard, reason, age, holder):
        """Atomically remove a stealable lease (rename to a unique
        grave, then unlink).  True when THIS caller won the rename —
        the shard is unleased again and open to normal claims."""
        if not lease_remove(_lease_path(self.out_dir, shard)):
            return False  # someone else stole/released it first
        metrics.counter("shards_stolen").inc()
        log_event("shard_steal", shard=shard, worker=self.worker_id,
                  from_worker=holder, reason=reason,
                  age_s=round(float(age), 3))
        return True

    # -- completion records

    def has_done(self, shard):
        return os.path.exists(_done_path(self.out_dir, shard))

    def read_done(self, shard):
        try:
            with open(_done_path(self.out_dir, shard)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def write_done(self, shard, **rec):
        rec.setdefault("worker", self.worker_id)
        rec.setdefault("t", time.time())
        ids = ambient_ids()
        if ids is not None and "trace_id" not in rec:
            rec["trace_id"], rec["parent_span_id"] = ids
        resilience._atomic_json(_done_path(self.out_dir, shard), rec)

    def done_count(self):
        return sum(1 for s in range(self.n_shards) if self.has_done(s))

    # -- worker status (the holder-staleness heartbeat + wall pooling)

    def worker_states(self):
        """Every worker's last status record (unreadable files skipped)."""
        out = {}
        try:
            names = fsops.listdir(_workers_dir(self.out_dir))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                out[name[:-5]] = json.loads(fsops.read_text(
                    os.path.join(_workers_dir(self.out_dir), name)))
            except (OSError, ValueError):
                continue
        return out

    def pooled_walls(self, states=None):
        """Fleet-wide ``shard_wall_s`` histogram: every worker's
        published bucket state merged (pass ``states`` to reuse one
        :meth:`worker_states` read across many shard checks).  Only a
        WORKER that has not yet published a status file folds in its
        own live registry — a coordinator's registry may hold an
        unrelated earlier sweep's observations (the same scoping
        problem the serial path solves with counter deltas)."""
        if states is None:
            states = self.worker_states()
        pooled = metrics.merge_states(
            [st.get("shard_wall_s") for st in states.values() if st],
            name="shard_wall_s_pooled")
        if self.worker_id is not None and self.worker_id not in states:
            pooled.merge_state(metrics.histogram("shard_wall_s").state())
        return pooled

    def write_worker_status(self, state, held=(), **extra):
        rec = {
            "worker": self.worker_id,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "t": time.time(),
            "state": state,
            "held": sorted(int(s) for s in held),
            "shard_wall_s": metrics.histogram("shard_wall_s").state(),
        }
        rec.update(extra)
        resilience._atomic_json(
            _worker_path(self.out_dir, self.worker_id), rec)

    def touch_worker(self):
        """Cheap liveness bump of this worker's status file (called
        from the lease renewer so a long shard keeps the holder's
        heartbeat fresh without a full status rewrite)."""
        try:
            fsops.utime(_worker_path(self.out_dir, self.worker_id))
        except OSError:
            pass

    def summary(self):
        """Ledger snapshot for the ``status`` CLI / tests."""
        now = time.time()
        leases = {}
        for s in range(self.n_shards):
            rec, mtime = self.read_lease(s)
            if rec is None:
                continue
            leases[s] = {
                "worker": rec.get("worker"),
                "attempt": rec.get("attempt"),
                "age_s": round(now - float(rec.get("renewed_t") or mtime
                                           or now), 3),
            }
        done = [s for s in range(self.n_shards) if self.has_done(s)]
        return {
            "n_shards": self.n_shards,
            "done": len(done),
            "leased": leases,
            "remaining": self.n_shards - len(done),
            "workers": {wid: {k: st.get(k) for k in
                              ("state", "held", "shards_done", "pid")}
                        for wid, st in self.worker_states().items()},
        }


# ------------------------------------------------------------ entry specs


def resolve_entry(entry, kwargs=None):
    """Import and call one fabric entry spec.

    ``entry`` is ``module:callable`` (importable from the repo root)
    or ``path/to/file.py:callable``.  The callable receives ``kwargs``
    and returns either the shard ``compute(chunk, mesh)`` callable or
    a dict with keys ``compute`` (required), ``cases``, ``warmup``.
    Returns the normalized dict."""
    if ":" not in entry:
        raise ValueError(
            f"bad fabric entry {entry!r} (want module:callable or "
            "path.py:callable)")
    target, attr = entry.rsplit(":", 1)
    if target.endswith(".py") or os.sep in target:
        spec = importlib.util.spec_from_file_location(
            "_raft_fabric_entry_" + os.path.basename(target)[:-3], target)
        if spec is None or spec.loader is None:
            raise ValueError(f"cannot load fabric entry file {target!r}")
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
    else:
        module = importlib.import_module(target)
    fn = getattr(module, attr)
    res = fn(**(kwargs or {}))
    if callable(res):
        res = {"compute": res}
    if not (isinstance(res, dict) and callable(res.get("compute"))):
        raise ValueError(
            f"fabric entry {entry!r} must return a compute callable or a "
            "dict with a 'compute' callable")
    return res


def demo_entry(out_keys=("PSD", "X0", "status"), n=256, seed=0,
               design=None, **_):
    """Built-in entry over the bundled spar design: the bench fabric
    block, the CLI quick start and the README recipe use it (runs
    without ``/root/reference``).  Returns compute + a deterministic
    (Hs, Tp, beta) case batch."""
    import raft_tpu
    from raft_tpu import api
    from raft_tpu.parallel.sweep import case_compute

    design = design or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "designs", "spar_demo.yaml")
    model = raft_tpu.Model(design)
    evaluate = api.make_case_evaluator(model)
    rng = np.random.default_rng(seed)
    cases = {
        "Hs": rng.uniform(2.0, 8.0, int(n)),
        "Tp": rng.uniform(6.0, 14.0, int(n)),
        "beta": rng.uniform(-0.5, 0.5, int(n)),
    }
    return {"compute": case_compute(evaluate, out_keys=tuple(out_keys)),
            "cases": cases}


# ----------------------------------------------------------------- worker


class _Renewer(threading.Thread):
    """Daemon thread renewing the held lease (+ touching the worker's
    status file) every ``ttl/3`` while a shard evaluates.  The
    ``lease_expire:lease_renew`` fault silences it permanently —
    the wedged-but-alive worker the straggler rules exist for."""

    def __init__(self, ledger, shard, silenced):
        super().__init__(name=f"raft-tpu-lease-{shard}", daemon=True)
        self.ledger = ledger
        self.shard = shard
        self.silenced = silenced  # 1-element list shared with the worker
        ttl = float(config.get("FABRIC_TTL_S"))
        self.interval_s = max(ttl / 3.0, 0.05)
        self._stop_evt = threading.Event()

    def run(self):
        while not self._stop_evt.wait(self.interval_s):
            if not self.silenced[0] and faults.take("lease_expire",
                                                    "lease_renew"):
                self.silenced[0] = True
            if self.silenced[0]:
                continue
            try:
                self.ledger.renew(self.shard)
                self.ledger.touch_worker()
            except Exception:
                pass  # renewal must never kill the evaluation

    def stop(self):
        self._stop_evt.set()
        self.join(timeout=2.0)


class Worker:
    """One fabric worker: claims shards from the ledger of ``out_dir``
    and evaluates them until the ledger is drained.  Run via
    :meth:`run` (CLI: ``python -m raft_tpu.parallel.fabric worker``)."""

    def __init__(self, out_dir, worker_id=None):
        self.out_dir = out_dir
        self.worker_id = (worker_id or config.raw("WORKER_ID")
                          or "w-" + uuid.uuid4().hex[:6])
        # ambient worker stamp: every structured-log record this
        # process emits carries worker=<id> (per-worker report tables)
        os.environ[config.env_name("WORKER_ID")] = self.worker_id
        self.held = set()
        self.shards_done = 0
        self.shards_resumed = 0
        self.rows = 0
        self._renew_silenced = [False]

    # -- jax runtime setup (mirrors tests/_aot_child.py: the axon
    # plugin overrides JAX_PLATFORMS at import, so pin via config too)

    def _setup_runtime(self, spec):
        import jax

        if (os.environ.get("JAX_PLATFORMS", "") or "").split(",")[0] == "cpu":
            jax.config.update("jax_platforms", "cpu")
        if spec.get("x64") is not None:
            jax.config.update("jax_enable_x64", bool(spec["x64"]))
        # multi-host wiring FIRST: jax.distributed.initialize must run
        # before warmup / entry model builds touch the backend, or the
        # worker's mesh would only ever span its local devices
        from raft_tpu.parallel.sweep import ensure_distributed

        ensure_distributed()
        from raft_tpu.utils.devices import enable_compile_cache

        enable_compile_cache()

    def run(self):
        """Join the sweep: warm up, then claim/evaluate/release until
        every shard has a completion record.  Returns the number of
        shards this worker computed."""
        t0 = time.perf_counter()
        spec = load_spec(self.out_dir)
        self.spec = spec
        self.out_keys = list(spec["out_keys"])
        self.shard_size = int(spec["shard_size"])
        self.n_cases = int(spec["n_cases"])
        self.n_shards = int(spec["n_shards"])
        self._setup_runtime(spec)
        cases = load_cases(self.out_dir)
        resilience.validate_manifest(
            self.out_dir,
            resilience.compute_fingerprint(cases, self.out_keys,
                                           self.shard_size, mesh=None))
        self.cases = cases
        self.ledger = Ledger(self.out_dir, self.n_shards,
                             worker_id=self.worker_id)
        self.ledger.write_worker_status("starting")

        warmup_s = None
        if spec.get("warmup") and config.get("AOT") != "off":
            warmup_s = self._warmup(spec["warmup"])
        entry = resolve_entry(spec["entry"], spec.get("entry_kwargs"))
        self.compute = entry["compute"]
        from raft_tpu.parallel.sweep import make_mesh

        self.mesh = resilience.resolve_mesh(make_mesh)

        counters0 = dict(metrics.snapshot()["counters"])
        self._counters0 = counters0
        start_kw = dict(
            out_dir=self.out_dir, worker=self.worker_id,
            n_shards=self.n_shards,
            programs_loaded=counters0.get("aot_programs_loaded", 0),
            programs_compiled=counters0.get("aot_programs_compiled", 0))
        if warmup_s is not None:
            start_kw["warmup_s"] = round(warmup_s, 2)
        # arm the flight recorder (no-op without RAFT_TPU_FLIGHT_DIR):
        # a preempted/OOM-killed worker leaves a black box with its
        # last shards' spans even when RAFT_TPU_LOG was never set
        from raft_tpu.obs import flight

        flight.maybe_start()
        log_event("fabric_worker_start", **start_kw)
        progress = {"out_dir": self.out_dir, "shards_done": 0,
                    "n_shards": self.n_shards}
        self.ledger.write_worker_status("ready")
        poll_s = float(config.get("FABRIC_POLL_S"))
        with maybe_heartbeat(devices=list(self.mesh.devices.flat),
                             progress=progress,
                             worker_id=self.worker_id,
                             leases=lambda: list(self.held)):
            while True:
                verdict, shard = self._scan_once()
                if verdict == "done":
                    break
                if verdict == "wait":
                    if not self._renew_silenced[0]:
                        self.ledger.touch_worker()
                    time.sleep(poll_s)
                    continue
                self._eval_shard(shard)
                progress["shards_done"] = self.shards_done

        cnt = metrics.snapshot()["counters"]
        from raft_tpu.aot import bank

        # warmup/AOT activity predates counters0 — report absolutes for
        # the program provenance, deltas for the sweep bookkeeping;
        # `programs` is this worker's device-cost ledger (per-program
        # flops/dispatches/achieved GFLOP/s), folded fleet-wide by the
        # coordinator's assemble and the bench fabric block
        self.ledger.write_worker_status(
            "done", counters=self._counter_delta(),
            shards_done=self.shards_done,
            shards_resumed=self.shards_resumed, rows=self.rows,
            programs_loaded=cnt.get("aot_programs_loaded", 0),
            programs_compiled=cnt.get("aot_programs_compiled", 0),
            programs=bank.ledger_summary())
        log_event("fabric_worker_done", out_dir=self.out_dir,
                  worker=self.worker_id, shards_done=self.shards_done,
                  shards_resumed=self.shards_resumed, rows=self.rows,
                  wall_s=round(time.perf_counter() - t0, 3),
                  programs_loaded=cnt.get("aot_programs_loaded", 0),
                  programs_compiled=cnt.get("aot_programs_compiled", 0))
        return self.shards_done

    def _warmup(self, warmup):
        """Push the entry's AOT warmup spec through the program bank
        before the first claim (PR-6 machinery): a mid-sweep joiner on
        a warmed bank answers its first shard compile-free.  Warmup
        failure is logged, never fatal — the first shard then simply
        pays the trace."""
        t0 = time.perf_counter()
        try:
            from raft_tpu.aot.warmup import warmup_model

            warmup_model(
                design=warmup.get("design"),
                sizes=tuple(warmup.get("sizes") or (self.shard_size,)),
                kinds=tuple(warmup.get("kinds") or ("cases",)),
                out_keys=tuple(warmup.get("out_keys") or self.out_keys))
        except Exception as e:
            log_event("aot_error", error=f"fabric warmup failed: {e}"[:300])
        return time.perf_counter() - t0

    def _shard_rows(self, shard):
        lo = shard * self.shard_size
        return min(lo + self.shard_size, self.n_cases) - lo

    def _counter_delta(self):
        """This worker's sweep-scoped counter deltas (published on
        EVERY status write, not just the final one — a worker killed
        mid-sweep must still contribute its completed shards' counters
        to the assembled metrics)."""
        cnt = metrics.snapshot()["counters"]
        return {k: v - self._counters0.get(k, 0) for k, v in cnt.items()
                if v - self._counters0.get(k, 0)}

    def _try_adopt(self, s, own_lease_ok=False):
        """Adopt an existing VALID shard file as done (resumed): done
        record, counters, ``shard_resume`` event.  False when the file
        is absent or corrupt (the caller decides whether to recompute)
        — the one adoption path for both the scan and the post-claim
        double-compute race.

        A shard under someone ELSE's lease is never adopted: its
        holder may be between ``atomic_savez`` and ``write_done``, and
        a racing ``resumed=True`` record would clobber the holder's
        richer one (quarantine entries, wall_s, attempt).  The scan
        skips it — the holder finishes or its lease expires and the
        normal steal path applies; ``own_lease_ok`` lets the
        post-claim check adopt under this worker's own fresh lease."""
        path = _shard_path(self.out_dir, s)
        if not os.path.exists(path):
            return False
        rec, _ = self.ledger.read_lease(s)
        if rec is not None and not (own_lease_ok
                                    and rec.get("token")
                                    == self.ledger.token):
            return False
        try:
            resilience.load_shard(path, self.out_keys,
                                  expect_rows=self._shard_rows(s))
        except resilience.ShardCorruptError:
            return False
        self.ledger.write_done(s, resumed=True, rows=self._shard_rows(s))
        self.shards_resumed += 1
        metrics.counter("shards_resumed").inc()
        log_event("shard_resume", shard=s, rows=self._shard_rows(s))
        return True

    def _scan_once(self):
        """One pass over the ledger.  Returns ``("claimed", s)`` /
        ``("wait", None)`` (work remains but every open shard is
        leased) / ``("done", None)``."""
        remaining = False
        n = self.n_shards
        pooled = None  # one worker_states read per PASS, not per shard
        # stagger scan starts per worker so a fresh fleet doesn't
        # serialize on the same O_EXCL races shard by shard
        start = (abs(hash(self.worker_id)) % n) if n else 0
        for i in range(n):
            s = (start + i) % n
            if self.ledger.has_done(s):
                continue
            if self._try_adopt(s):
                continue
            remaining = True
            if self.ledger.claim(s):
                return "claimed", s
            if pooled is None:
                pooled = self.ledger.pooled_walls()
            reason, age, holder, attempt = self.ledger.stealable(
                s, pooled=pooled)
            if reason and self.ledger.steal(s, reason, age, holder):
                if self.ledger.claim(s, attempt=attempt + 1):
                    return "claimed", s
        return ("wait", None) if remaining else ("done", None)

    def _eval_shard(self, s):
        if faults.take("worker_kill", "worker_shard"):
            # simulate a preempted/OOM-killed host: no cleanup, no
            # lease release — recovery is the OTHER workers' job
            os.kill(os.getpid(), signal.SIGKILL)
        path = _shard_path(self.out_dir, s)
        if self._try_adopt(s, own_lease_ok=True):
            # a double-compute race landed a valid shard between our
            # scan and our claim
            self.ledger.release(s)
            return
        if os.path.exists(path):
            # present but corrupt (truncated write of a dead worker):
            # requeue by recomputing under our fresh lease
            metrics.counter("shards_corrupt").inc()
            log_event("shard_corrupt", shard=s,
                      error=f"{path}: failed validation on claim")
            try:
                fsops.unlink(path)
            except OSError:
                pass
        self.held.add(s)
        renewer = _Renewer(self.ledger, s, self._renew_silenced)
        renewer.start()
        sl = slice(s * self.shard_size,
                   min((s + 1) * self.shard_size, self.n_cases))
        chunk = {k: v[sl] for k, v in self.cases.items()}
        try:
            out, entries, wall = resilience.evaluate_shard(
                self.compute, chunk, s, sl.start, self.mesh,
                max_retries=int(self.spec.get("max_retries", 3)),
                backoff_s=float(self.spec.get("backoff_s", 0.5)),
                quarantine_retry=bool(self.spec.get("quarantine_retry",
                                                    True)),
                on_result=lambda out_, _e: resilience.atomic_savez(
                    path, **out_))
            self.ledger.write_done(
                s, wall_s=round(wall, 3), rows=sl.stop - sl.start,
                attempt=self._lease_attempt(s),
                quarantined=sum(1 for e in entries
                                if not e.get("resolved")),
                flagged=int(len(resilience.flagged_rows(out))),
                entries=entries)
            self.shards_done += 1
            self.rows += sl.stop - sl.start
        finally:
            renewer.stop()
            self.held.discard(s)
            self.ledger.release(s)
        if not self._renew_silenced[0]:
            self.ledger.write_worker_status(
                "running", held=self.held, shards_done=self.shards_done,
                counters=self._counter_delta())

    def _lease_attempt(self, s):
        rec, _ = self.ledger.read_lease(s)
        return int((rec or {}).get("attempt") or 1)


# ------------------------------------------------------------ coordinator


def init_sweep(out_dir, entry, cases, out_keys, shard_size,
               entry_kwargs=None, warmup=None, max_retries=3,
               backoff_s=0.5, quarantine_retry=True):
    """Write the sweep spec + case arrays + manifest so workers can
    join.  Never touches jax (a coordinator stays a cheap process);
    resuming against an existing out_dir is manifest-validated exactly
    like the serial runner.  Returns the spec dict."""
    cases = {k: np.asarray(v) for k, v in cases.items()}
    lengths = {k: len(v) for k, v in cases.items()}
    if len(set(lengths.values())) > 1:
        raise ValueError(
            f"ragged case dict: all case arrays must have equal length, "
            f"got {lengths}")
    n = next(iter(lengths.values()))
    n_shards = (n + shard_size - 1) // shard_size
    fsops.makedirs(fabric_dir(out_dir), exist_ok=True)
    fingerprint = resilience.compute_fingerprint(cases, out_keys,
                                                 shard_size, mesh=None)
    resilience.init_manifest(out_dir, fingerprint, n_shards)
    resilience._atomic_write(_cases_path(out_dir),
                             lambda f: np.savez(f, **cases))
    x64 = None
    if "jax" in sys.modules:
        import jax

        x64 = bool(jax.config.jax_enable_x64)
    spec = {
        "version": 1,
        "entry": str(entry),
        "entry_kwargs": dict(entry_kwargs or {}),
        "out_keys": list(out_keys),
        "shard_size": int(shard_size),
        "n_cases": int(n),
        "n_shards": int(n_shards),
        "x64": x64,
        "warmup": warmup,
        "max_retries": int(max_retries),
        "backoff_s": float(backoff_s),
        "quarantine_retry": bool(quarantine_retry),
    }
    resilience._atomic_json(_spec_path(out_dir), spec)
    Ledger(out_dir, n_shards)  # create the ledger directories
    log_event("fabric_init", out_dir=out_dir, n_cases=n,
              n_shards=n_shards, shard_size=int(shard_size),
              entry=str(entry))
    return spec


def _worker_device_env(index, workers):
    """Per-worker accelerator pinning: slice CUDA_VISIBLE_DEVICES-style
    lists round-robin when the parent exposes one; CPU containers need
    nothing (each worker is its own host-platform process)."""
    for var in ("CUDA_VISIBLE_DEVICES", "HIP_VISIBLE_DEVICES"):
        raw = os.environ.get(var, "")
        devs = [d for d in raw.split(",") if d.strip()]
        if len(devs) >= workers > 1:
            return {var: ",".join(devs[index::workers])}
    return {}


def spawn_worker(out_dir, index=0, worker_id=None, env=None,
                 workers_total=1):
    """Spawn one worker subprocess against ``out_dir``'s ledger.
    stdout/stderr land in ``_fabric/workers/<wid>.log``.  Returns
    ``(Popen, worker_id)``."""
    wid = worker_id or f"w{index}"
    wenv = dict(os.environ)
    wenv.update(_worker_device_env(index, int(workers_total)))
    # telemetry linkage (the 5-unlinked-timelines bug): pin the
    # coordinator's run id into every worker so their structlog records
    # and heartbeats join the parent run instead of minting fresh
    # uuids, and hand them the enclosing sweep span as traceparent so
    # worker shard spans resolve into the coordinator's trace
    wenv.update(propagation_env())
    wenv.update(env or {})
    wenv[config.env_name("WORKER_ID")] = wid
    root = _repo_root()
    old_pp = wenv.get("PYTHONPATH", "")
    wenv["PYTHONPATH"] = root + (os.pathsep + old_pp if old_pp else "")
    # worker-targeted fault kinds go to exactly one worker
    fspecs = wenv.get(config.env_name("FAULTS"), "")
    if fspecs and index != int(config.get("FABRIC_FAULT_WORKER")):
        kept = [s for s in fspecs.split(",") if s.strip()
                and s.strip().split(":")[0] not in ("worker_kill",
                                                    "lease_expire")]
        wenv[config.env_name("FAULTS")] = ",".join(kept)
    fsops.makedirs(_workers_dir(out_dir), exist_ok=True)
    logf = open(os.path.join(_workers_dir(out_dir), f"{wid}.log"), "ab")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "raft_tpu.parallel.fabric", "worker",
             "--out-dir", os.path.abspath(out_dir), "--worker-id", wid],
            env=wenv, stdout=logf, stderr=subprocess.STDOUT, cwd=root)
    finally:
        logf.close()  # the child keeps its own handle
    log_event("fabric_worker_spawn", out_dir=out_dir, worker=wid,
              pid=proc.pid)
    return proc, wid


def _log_tail(out_dir, wid, n=12):
    try:
        with open(os.path.join(_workers_dir(out_dir), f"{wid}.log"),
                  errors="replace") as f:
            return [ln.rstrip() for ln in f.readlines()[-n:]]
    except OSError:
        return []


def run_fabric(out_dir, workers, entry, cases=None, entry_kwargs=None,
               out_keys=("PSD", "X0"), shard_size=256, warmup=None,
               on_shard=None, worker_env=None, max_retries=3,
               backoff_s=0.5, quarantine_retry=True):
    """Coordinator: initialize the ledger, spawn ``workers`` local
    worker subprocesses, wait for the ledger to drain, assemble.

    ``cases=None`` resolves the entry in-process and takes the case
    arrays from its result dict (the pure-CLI path).  Returns the
    concatenated result dict, exactly like the serial
    :func:`~raft_tpu.parallel.resilience.run_checkpointed` — same
    shards, same manifest, same quarantine.json, bit-identical
    values."""
    t0 = time.perf_counter()
    if cases is None:
        res = resolve_entry(entry, entry_kwargs)
        cases = res.get("cases")
        if cases is None:
            raise ValueError(
                f"fabric entry {entry!r} returned no case arrays; pass "
                "cases= explicitly or make the entry return "
                "{'compute': ..., 'cases': ...}")
        warmup = warmup if warmup is not None else res.get("warmup")
    spec = init_sweep(out_dir, entry, cases, out_keys, shard_size,
                      entry_kwargs=entry_kwargs, warmup=warmup,
                      max_retries=max_retries, backoff_s=backoff_s,
                      quarantine_retry=quarantine_retry)
    n_shards = spec["n_shards"]
    log_event("sweep_start", out_dir=out_dir, n_cases=spec["n_cases"],
              n_shards=n_shards, shard_size=spec["shard_size"],
              out_keys=list(out_keys), mesh_shape=[])
    with span("sweep", out_dir=out_dir, n_cases=spec["n_cases"],
              n_shards=n_shards, fabric_workers=int(workers)):
        ledger = Ledger(out_dir, n_shards)
        procs = [spawn_worker(out_dir, index=i, env=worker_env,
                              workers_total=int(workers))
                 for i in range(int(workers))]
        poll_s = float(config.get("FABRIC_POLL_S"))
        reported = set()

        def report_progress():
            for s in sorted(set(range(n_shards)) - reported):
                if not ledger.has_done(s):
                    continue
                reported.add(s)
                if on_shard is not None:
                    rec = ledger.read_done(s) or {}
                    on_shard(len(reported), n_shards,
                             not rec.get("resumed", False))

        while True:
            report_progress()
            if len(reported) >= n_shards:
                break
            if all(p.poll() is not None for p, _ in procs):
                report_progress()
                if len(reported) >= n_shards:
                    break
                tails = {wid: _log_tail(out_dir, wid) for _, wid in procs}
                raise FabricError(
                    f"all {len(procs)} workers exited with "
                    f"{n_shards - len(reported)}/{n_shards} shards "
                    f"incomplete; worker log tails: "
                    + json.dumps(tails)[:2000])
            time.sleep(poll_s)

        for p, wid in procs:
            try:
                rc = p.wait(timeout=max(
                    10.0, 3 * float(config.get("FABRIC_TTL_S"))))
            except subprocess.TimeoutExpired:
                p.terminate()
                rc = p.wait(timeout=10.0)
            log_event("fabric_worker_exit", out_dir=out_dir, worker=wid,
                      returncode=rc)
        out = assemble(out_dir, spec, wall_s=time.perf_counter() - t0)
    return out


def assemble(out_dir, spec=None, wall_s=None):
    """Validate every shard, merge worker quarantine/metrics records
    into the standard artifacts (quarantine.json, manifest statuses,
    metrics.json) and return the concatenated result dict."""
    t0 = time.perf_counter()
    spec = spec or load_spec(out_dir)
    out_keys = list(spec["out_keys"])
    n_shards = int(spec["n_shards"])
    n_cases = int(spec["n_cases"])
    shard_size = int(spec["shard_size"])
    ledger = Ledger(out_dir, n_shards)
    try:
        with open(resilience._manifest_path(out_dir)) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise FabricError(f"unreadable manifest in {out_dir}: {e}") from e
    manifest.setdefault("shards", {})

    results = []
    n_quarantined = 0
    n_flagged = 0
    for s in range(n_shards):
        rows = min((s + 1) * shard_size, n_cases) - s * shard_size
        try:
            out = resilience.load_shard(_shard_path(out_dir, s), out_keys,
                                        expect_rows=rows)
        except resilience.ShardCorruptError as e:
            raise FabricError(
                f"assembly found shard {s} missing/corrupt: {e}") from e
        rec = ledger.read_done(s) or {}
        entries = rec.get("entries") or []
        # only shards COMPUTED this run re-judge their quarantine
        # entries; an adopted (resumed) shard carries no entries in its
        # done record, and replacing its slice with [] would erase the
        # prior run's audit while the bad rows are still in the shard —
        # the serial resume path leaves quarantine.json alone too
        if not rec.get("resumed") and (
                entries or os.path.exists(
                    resilience._quarantine_path(out_dir))):
            resilience.record_quarantine(out_dir, s, entries)
        # same accounting as a serial resume: rows still bad in the
        # stored shard are this sweep's quarantined rows
        bad = len({int(i) for i in resilience.nonfinite_rows(out)}
                  | {int(i) for i in resilience.flagged_rows(out)})
        flagged = len(resilience.flagged_rows(out))
        n_quarantined += bad
        n_flagged += flagged
        srec = {"status": "done", "file": f"shard_{s:04d}.npz",
                "rows": rows, "quarantined": bad, "flagged": flagged}
        for k in ("worker", "wall_s", "attempt", "resumed"):
            if rec.get(k) is not None:
                srec[k] = rec[k]
        manifest["shards"][str(s)] = srec
        results.append(out)

    # fold every worker's sweep-delta counters into this process's
    # registry (so e.g. sweep_10k's summary — which reads the local
    # metrics snapshot — sees the fleet totals) and into metrics.json
    states = ledger.worker_states()
    counters = {}
    for st in states.values():
        for k, v in (st.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + int(v)
    for k, v in counters.items():
        metrics.counter(k).inc(v)
    pooled = ledger.pooled_walls()
    from raft_tpu.aot import bank

    snap = {
        "counters": counters,
        "gauges": {},
        "histograms": {"shard_wall_s": pooled.snapshot()},
        "workers": {wid: {k: st.get(k) for k in
                          ("state", "shards_done", "shards_resumed",
                           "rows", "programs_loaded", "programs_compiled",
                           "pid", "host")}
                    for wid, st in states.items()},
        # fleet-wide device-cost ledger: every worker's per-program
        # flops/dispatch stats merged (bench fabric block reads this)
        "programs": bank.merge_ledgers(
            [st.get("programs") for st in states.values()]),
    }
    manifest["metrics"] = snap
    resilience._atomic_json(resilience._manifest_path(out_dir), manifest)
    try:
        resilience._atomic_json(os.path.join(out_dir,
                                             resilience.METRICS_NAME), snap)
    except OSError:
        pass  # telemetry must not fail the sweep that produced it
    prom_path = config.get("METRICS")
    if prom_path:
        metrics.export(prom_path)
    log_event("fabric_assemble", out_dir=out_dir, n_shards=n_shards,
              n_workers=len(states), n_quarantined=n_quarantined,
              n_flagged=n_flagged,
              wall_s=round(time.perf_counter() - t0, 3))
    log_event("sweep_done", out_dir=out_dir, n_cases=n_cases,
              n_quarantined=n_quarantined, n_flagged=n_flagged,
              wall_s=round(wall_s if wall_s is not None
                           else time.perf_counter() - t0, 3))
    # longitudinal run record for the fabric-assembled sweep, same as
    # the serial path (the coordinator's registry now holds the folded
    # worker counters + the pooled shard-wall histogram)
    from raft_tpu.obs import runs as obs_runs

    obs_runs.maybe_record(
        "sweep", label=os.path.basename(os.path.normpath(out_dir)),
        wall_s=(wall_s if wall_s is not None
                else time.perf_counter() - t0),
        extra={"n_cases": n_cases, "n_shards": n_shards,
               "n_workers": len(states), "n_quarantined": n_quarantined,
               "n_flagged": n_flagged})
    return {k: np.concatenate([r[k] for r in results]) for k in out_keys}


# -------------------------------------------------------------------- CLI


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m raft_tpu.parallel.fabric",
        description="elastic multi-worker sweep fabric")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("worker", help="join a sweep as one worker")
    p.add_argument("--out-dir", required=True)
    p.add_argument("--worker-id", default=None)

    p = sub.add_parser("run", help="coordinate N local workers")
    p.add_argument("--out-dir", required=True)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--entry",
                   default="raft_tpu.parallel.fabric:demo_entry",
                   help="module:callable or path.py:callable returning "
                        "{'compute', 'cases'} (default: bundled spar demo)")
    p.add_argument("--entry-kwargs", default="{}",
                   help="JSON kwargs for the entry callable")
    p.add_argument("--out-keys", default="PSD,X0,status")
    p.add_argument("--shard", type=int, default=64)

    p = sub.add_parser("status", help="print the ledger summary")
    p.add_argument("--out-dir", required=True)

    args = ap.parse_args(argv)
    if args.cmd == "worker":
        Worker(args.out_dir, worker_id=args.worker_id).run()
        return 0
    if args.cmd == "run":
        out = run_fabric(args.out_dir, workers=args.workers,
                         entry=args.entry,
                         entry_kwargs=json.loads(args.entry_kwargs),
                         out_keys=tuple(args.out_keys.split(",")),
                         shard_size=args.shard)
        print(json.dumps({k: list(np.asarray(v).shape)
                          for k, v in out.items()}))
        return 0
    if args.cmd == "status":
        spec = load_spec(args.out_dir)
        print(json.dumps(Ledger(args.out_dir,
                                spec["n_shards"]).summary(), indent=1))
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
