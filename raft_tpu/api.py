"""High-level traced evaluation API: one design evaluation as a pure
jax function, ready to jit / vmap / shard_map.

The reference evaluates one (design, load case) pair by a long chain of
Python method calls mutating FOWT state (Model.analyzeCases,
raft_model.py:264-433).  Here the same chain — static equilibrium →
wave excitation → iterative drag linearisation → impedance solve →
response statistics — is closed over the build-time structure and
exposed as ``evaluate(Hs, Tp, beta)``:

* jit once, then every additional (case x design-parameter) evaluation
  is a batched tensor program;
* ``vmap`` adds case/sea-state axes;
* device-mesh sharding (see :mod:`raft_tpu.parallel.sweep`) scales the
  batch across a TPU pod with XLA inserting the collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.models.dynamics import solve_dynamics_fowt, system_response
from raft_tpu.models.statics_solve import solve_equilibrium
from raft_tpu.physics import morison
from raft_tpu.physics.mooring import mooring_stiffness
from raft_tpu.physics.statics import calc_statics, node_T, platform_kinematics
from raft_tpu.ops import waves as wv


def make_case_evaluator(model, n_stat_iter=12):
    """Build ``evaluate(Hs, Tp, beta) -> outputs`` for one design.

    All build-time structure (strips, topology, statics matrices) is
    resolved here; the returned function is pure jax on scalar sea-state
    inputs and fully differentiable.
    """
    fs = model.fowtList[0]
    ms = model.ms
    fh = model.hydro[0]
    ss = fh.strips
    w = jnp.asarray(model.w)
    k = jnp.asarray(model.k)
    dw = model.w[1] - model.w[0]
    nw = model.nw
    nDOF = fs.nDOF

    # closures stay host-side numpy: they lower to jit constants without
    # any device pull (the axon TPU tunnel only implements f32 d2h)
    stat = model.statics()
    K_h = np.asarray(stat["C_struc"] + stat["C_hydro"])
    F_und = np.asarray(stat["W_struc"] + stat["W_hydro"] + stat["f0_additional"])
    M_struc = np.asarray(stat["M_struc"])
    A_hydro = np.asarray(fh.hc0["A_hydro"])
    hc0 = fh.hc0

    def evaluate(Hs, Tp, beta):
        # --- mean offsets under zero mean environmental load
        X0, _ = solve_equilibrium(fs, ms, K_h, F_und, jnp.zeros(nDOF))

        # --- pose-dependent geometry
        r_nodes, R_ptfm, r_root = platform_kinematics(fs, X0)
        Tn = node_T(r_nodes, r_root)
        r, q, p1, p2 = morison.strip_frames(ss, R_ptfm, r_nodes)
        sub = r[:, 2] < 0
        hc = dict(hc0, r=r, q=q, p1=p1, p2=p2, sub=sub,
                  active=sub & jnp.asarray(ss.active))

        # --- sea state + excitation
        S = wv.jonswap(w, Hs, Tp)
        zeta = jnp.sqrt(2.0 * S * dw).astype(complex)
        exc = morison.hydro_excitation(
            fs, ss, hc, zeta[None, :], jnp.asarray([beta]), w, k, Tn, r_nodes
        )

        # --- linear system + iterative drag linearisation
        C_moor = jnp.zeros((nDOF, nDOF))
        if ms is not None:
            C_moor = C_moor.at[:6, :6].add(mooring_stiffness(ms, X0[:6]))
        M_lin = jnp.broadcast_to((M_struc + A_hydro)[:, :, None], (nDOF, nDOF, nw))
        B_lin = jnp.zeros((nDOF, nDOF, nw))
        C_lin = K_h + C_moor
        F_lin = exc["F_hydro_iner"][0]

        Z, Xi1, Bmat = solve_dynamics_fowt(
            fs, ss, hc, exc["u"][0], M_lin, B_lin, C_lin, F_lin,
            w, Tn, r_nodes, n_iter=model.nIter, Xi_start=model.XiStart,
        )
        F_wave = F_lin * 0 + exc["F_hydro_iner"][0] + morison.drag_excitation(
            fs, ss, hc, Bmat, exc["u"][0], Tn, r_nodes
        )
        Xi = system_response(Z, F_wave[None])[0]  # (nDOF, nw)

        RAO = wv.get_rao(Xi, zeta)
        PSD = 0.5 * jnp.abs(Xi) ** 2 / dw
        return dict(X0=X0, Xi=Xi, RAO=RAO, PSD=PSD, S=S)

    return evaluate
