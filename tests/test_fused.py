"""Fused case hot path: parity harness + dispatch-count contract.

ROADMAP item 5c: under ``RAFT_TPU_FUSED=on`` (the default) the rigid
single-heading evaluators take their wave response straight from the
drag fixed point's final solve — the per-ω excitation assembly (the
separable drag-excitation fold of ``drag_lin_precompute``) is fused
into the drag-linearised solve program instead of re-staged as a
separate ``drag_excitation`` chain + second batched solve.

Contract (tests here, budgets in analysis/jaxpr_contracts.py entry
``fused_case``):

* fused vs staged (``RAFT_TPU_FUSED=off``) parity <= 1e-10 on every
  float output, bit-equal int32 status, on ALL THREE bundled designs
  (spar + semi + MHK) — fold-vs-chain summation order is the only
  difference, measured at ~1e-15;
* a case evaluation through the sweep funnel is ONE banked program
  dispatch (one ``sweep_dispatch`` span), and a repeat dispatch
  compiles NOTHING.
"""

import json
import os

import jax
import numpy as np
import pytest

import raft_tpu
from raft_tpu.analysis.recompile import count_compilations
from raft_tpu.api import make_case_evaluator
from raft_tpu.parallel.sweep import make_mesh, sweep_heterogeneous

HERE = os.path.dirname(os.path.abspath(__file__))
DESIGNS = os.path.join(HERE, "..", "raft_tpu", "designs")

CASES = [(6.0, 12.0, 0.0), (2.5, 7.5, 0.35)]


@pytest.fixture(scope="module")
def bundled_trio():
    return [raft_tpu.Model(os.path.join(DESIGNS, f)) for f in
            ("spar_demo.yaml", "semi_demo.yaml", "mhk_demo.yaml")]


@pytest.mark.slow
def test_fused_vs_staged_parity_bundled_trio(bundled_trio, monkeypatch):
    """Fused path <= 1e-10 vs the staged tail on spar + semi + MHK,
    int32 status bit-equal."""
    for model in bundled_trio:
        res = {}
        for mode in ("on", "off"):
            monkeypatch.setenv("RAFT_TPU_FUSED", mode)
            ev = jax.jit(make_case_evaluator(model))
            res[mode] = [{k: np.asarray(v) for k, v in ev(*c).items()}
                         for c in CASES]
        for i in range(len(CASES)):
            fused, staged = res["on"][i], res["off"][i]
            assert int(fused["status"]) == int(staged["status"])
            assert fused["status"].dtype == np.int32
            for k in ("X0", "Xi", "RAO", "PSD", "S"):
                np.testing.assert_allclose(
                    fused[k], staged[k], rtol=1e-10, atol=1e-12,
                    err_msg=f"{model.design.get('name')} case {i} {k}")


@pytest.mark.slow
def test_one_banked_program_per_case_dispatch(bundled_trio, tmp_path,
                                              monkeypatch):
    """A fused case eval through the sweep funnel is ONE program
    dispatch, and the steady state recompiles nothing."""
    monkeypatch.delenv("RAFT_TPU_FUSED", raising=False)
    spar = bundled_trio[0]
    mesh = make_mesh(1)
    log = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", log)
    out = sweep_heterogeneous([spar], [5.0], [11.0], [0.1], mesh=mesh,
                              out_keys=("PSD", "X0", "status"))
    with open(log) as f:
        evs = [json.loads(x) for x in f if x.strip()]
    disp = [e for e in evs if e["event"] == "span_begin"
            and e.get("name") == "sweep_dispatch"]
    assert len(disp) == 1  # ONE banked program ran the whole case
    with count_compilations() as clog:
        out2 = sweep_heterogeneous([spar], [5.0], [11.0], [0.1],
                                   mesh=mesh,
                                   out_keys=("PSD", "X0", "status"))
    assert clog.count == 0  # steady state: zero backend events
    for k in ("PSD", "X0", "status"):
        np.testing.assert_array_equal(out[k], out2[k])
    # and the fused dispatch matches the solo fused evaluator
    ref = jax.jit(make_case_evaluator(spar))(5.0, 11.0, 0.1)
    np.testing.assert_allclose(out["PSD"][0], np.asarray(ref["PSD"]),
                               rtol=1e-10, atol=1e-12)
    assert int(out["status"][0]) == int(np.asarray(ref["status"]))
