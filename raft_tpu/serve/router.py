"""Consistent-hash failover router for the horizontal serving fleet.

The thin front of ROADMAP item 4: a stdlib asyncio process that owns
fleet **membership** (off the ``_fleet/`` lease ledger —
:mod:`raft_tpu.serve.fleet`) and the **robustness ladder**, and proxies
``POST /evaluate`` to replica servers so clients see ONE durable
endpoint while replicas die, drain and join underneath:

* **consistent-hash affinity** — requests hash by ``(bucket signature,
  design content hash)`` (:func:`routing_key`) onto a vnode ring
  (:class:`HashRing`), so a repeated design always lands on the same
  replica and replica result/program caches stay hot; adding or
  removing a replica moves only the keys it owns (tier-1-asserted);
* **failover retries** — a connect failure, dropped response,
  per-attempt timeout, or retryable 5xx (500/502/503) moves the
  request to the next ring replica after a capped exponential backoff
  (``Retry-After`` honored; shared schedule with the client —
  :func:`raft_tpu.serve.client.backoff_delay`).  Re-dispatch is safe
  by construction: serving evaluations are content-addressed
  (cache key = design hash + exact case bits + flags), so a duplicate
  dispatch is benign — the same argument that makes fabric
  double-compute benign;
* **per-replica circuit breaker** — ``RAFT_TPU_ROUTER_BREAKER_FAILS``
  consecutive failures open the breaker (``breaker_open`` event);
  after ``ROUTER_BREAKER_COOLDOWN_S`` one half-open trial (live
  request or ledger-prober ``/healthz`` probe) closes it again
  (``breaker_close``);
* **hedged requests** — with ``RAFT_TPU_ROUTER_HEDGE_MS`` set, a
  first attempt still unanswered after that long fires a second copy
  at the next ring replica and the first good response wins (p99
  straggler insurance; off by default);
* **graceful degradation** — only when every owning replica is dead or
  breaker-open does the client see ``503`` + ``Retry-After``
  (``router_reject``).

Membership runs on a daemon **prober thread** (file + HTTP probe IO
stays off the event loop): every ``RAFT_TPU_ROUTER_PROBE_S`` it reads
the lease ledger, health-checks joiners over ``/healthz`` before
admitting them to the ring, evicts expired leases (atomic rename —
exactly one evictor), closes breakers whose replica answers probes
again, and publishes the router's membership view to
``_fleet/router.json``.  Join and drain need NO router restart: a new
replica warms, claims, and takes traffic on the prober's next pass; a
draining replica releases its lease at drain start and the ring drops
it while its accepted work finishes.
"""

from __future__ import annotations

import asyncio
import bisect
import hashlib
import http.client
import json
import os
import signal
import threading
import time

from raft_tpu.obs import metrics
from raft_tpu.obs.spans import format_traceparent, parse_traceparent, span
from raft_tpu.serve import fleet, wire
from raft_tpu.serve.client import backoff_delay
from raft_tpu.utils import config
from raft_tpu.utils.structlog import log_event

_T0 = time.perf_counter()

#: upstream HTTP statuses the failover ladder treats as retryable:
#: 500 (replica bug / injected 5xx), 502, and 503 (draining replica /
#: full admission queue — another replica may have room).  429 is NOT
#: here: per-client quota is the client's problem on every replica.
RETRYABLE_STATUSES = (500, 502, 503)


def _hash64(s):
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "big")


def routing_key(payload, designs=None):
    """The ring key of one /evaluate payload: ``(bucket signature,
    design content hash)``.

    ``designs`` maps served design name -> {"sig", "fingerprint"}
    (merged from the lease bodies), so a named design routes by its
    bucket-signature fingerprint + content hash; an inline design
    routes by the hash of its JSON body (same design re-posted = same
    replica = warm inline-entry and result caches); an unknown name
    routes by the name itself (the owning replica answers the 404)."""
    if isinstance(payload, dict) and payload.get("design_inline") is not None:
        blob = json.dumps(payload["design_inline"], sort_keys=True,
                          default=str)
        return "|inline:" + hashlib.sha256(blob.encode()).hexdigest()[:24]
    name = str((payload or {}).get("design"))
    d = (designs or {}).get(name) or {}
    sig = str(d.get("sig") or "")
    dk = str(d.get("fingerprint") or "design:" + name)
    return f"{sig}|{dk}"


class HashRing:
    """Consistent-hash ring with virtual nodes.  Pure data structure —
    :class:`RouterState` serializes access under its lock."""

    def __init__(self, vnodes=None):
        self.vnodes = int(vnodes if vnodes is not None
                          else config.get("ROUTER_VNODES"))
        self._points = []    # sorted [(hash, replica_id)]
        self._members = set()

    def __len__(self):
        return len(self._members)

    def __contains__(self, rid):
        return rid in self._members

    def members(self):
        return sorted(self._members)

    def add(self, rid):
        if rid in self._members:
            return
        self._members.add(rid)
        for i in range(self.vnodes):
            bisect.insort(self._points, (_hash64(f"{rid}#{i}"), rid))

    def remove(self, rid):
        if rid not in self._members:
            return
        self._members.discard(rid)
        self._points = [p for p in self._points if p[1] != rid]

    def owners(self, key, n=None):
        """Distinct replicas clockwise from ``key``'s ring position —
        ``owners(key)[0]`` is the affinity owner, the rest are the
        failover order.  Stability property (tier-1-asserted): removing
        a replica never changes the owner of a key it did not own."""
        if not self._points:
            return []
        n = len(self._members) if n is None else min(n, len(self._members))
        i = bisect.bisect_right(self._points, (_hash64(key), ""))
        out = []
        for j in range(len(self._points)):
            rid = self._points[(i + j) % len(self._points)][1]
            if rid not in out:
                out.append(rid)
                if len(out) >= n:
                    break
        return out


class Breaker:
    """Per-replica circuit breaker.

    closed --``fails`` consecutive failures--> open --``cooldown_s``-->
    half-open (ONE trial admitted) --success--> closed / --failure-->
    open again.  ``clock`` is injectable for deterministic tests.
    Transitions are returned (``"open"``/``"close"``) so the owner can
    emit the registered events exactly once per transition."""

    def __init__(self, fails=None, cooldown_s=None, clock=time.monotonic):
        self.fails = int(fails if fails is not None
                         else config.get("ROUTER_BREAKER_FAILS"))
        self.cooldown_s = float(cooldown_s if cooldown_s is not None
                                else config.get("ROUTER_BREAKER_COOLDOWN_S"))
        self._clock = clock
        self._consecutive = 0
        self._opened_t = None       # None = closed
        self._trial_inflight = False

    @property
    def state(self):
        if self._opened_t is None:
            return "closed"
        if self._clock() - self._opened_t >= self.cooldown_s:
            return "half_open"
        return "open"

    def retry_after_s(self):
        """Seconds until this breaker would admit a half-open trial."""
        if self._opened_t is None:
            return 0.0
        return max(0.0, self.cooldown_s - (self._clock() - self._opened_t))

    def allow(self):
        """May a request be sent now?  Half-open admits exactly one
        in-flight trial at a time."""
        st = self.state
        if st == "closed":
            return True
        if st == "open":
            return False
        if self._trial_inflight:
            return False
        self._trial_inflight = True
        return True

    def record_success(self):
        was_open = self._opened_t is not None
        self._consecutive = 0
        self._trial_inflight = False
        self._opened_t = None
        return "close" if was_open else None

    def record_failure(self):
        st = self.state
        self._consecutive += 1
        self._trial_inflight = False
        if st == "half_open" or (st == "closed"
                                 and self._consecutive >= self.fails):
            self._opened_t = self._clock()
            return "open"
        if st == "open":
            self._opened_t = self._clock()  # extend the cooldown
        return None

    def release_trial(self):
        """Un-take a half-open trial slot without recording an outcome
        (the attempt was cancelled before completing — hedge loser)."""
        self._trial_inflight = False


class RouterState:
    """Membership + breaker state shared between the asyncio request
    path and the ledger-prober thread."""

    def __init__(self, vnodes=None):
        self._lock = threading.Lock()
        self._replicas = {}  # raft-lint: guarded-by=self._lock
        self._designs = {}   # raft-lint: guarded-by=self._lock
        self._breakers = {}  # raft-lint: guarded-by=self._lock
        self._ring = HashRing(vnodes)  # raft-lint: guarded-by=self._lock

    # ---------------------------------------------------- membership

    def apply_membership(self, live):
        """Reconcile the ring against ``{replica_id: lease_record}``
        (the ledger's live set).  Returns ``(added, removed,
        replaced)`` — ``replaced`` is the same-rid *endpoint* changes
        (a rolling-upgrade takeover re-binds the replica id to a new
        port): the rid keeps its vnodes, so NO key moves and no other
        replica's assignment is touched; only its breaker resets (the
        old endpoint's failure history says nothing about the new
        process)."""
        with self._lock:
            added = sorted(set(live) - set(self._replicas))
            removed = sorted(set(self._replicas) - set(live))
            replaced = []
            for rid in removed:
                self._ring.remove(rid)
                self._replicas.pop(rid, None)
                self._breakers.pop(rid, None)
            for rid, rec in live.items():
                old = self._replicas.get(rid)
                info = {
                    "addr": str(rec.get("addr") or "127.0.0.1"),
                    "port": int(rec.get("port") or 0),
                    "designs": dict(rec.get("designs") or {}),
                    "out_keys": list(rec.get("out_keys") or ()),
                    "healthz": dict(rec.get("healthz") or {}),
                }
                if old is not None and (old["addr"], old["port"]) != \
                        (info["addr"], info["port"]):
                    replaced.append(rid)
                    self._breakers[rid] = Breaker()
                self._replicas[rid] = info
                if rid not in self._ring:
                    self._ring.add(rid)
                self._breakers.setdefault(rid, Breaker())
            designs = {}
            for info in self._replicas.values():
                for name, d in info["designs"].items():
                    designs.setdefault(name, dict(d or {}))
            self._designs = designs
        return added, removed, sorted(replaced)

    def endpoint(self, rid):
        with self._lock:
            info = self._replicas.get(rid)
            return (info["addr"], info["port"]) if info else None

    def key_of(self, payload):
        with self._lock:
            return routing_key(payload, self._designs)

    def design_fingerprints(self):
        """{design name: content fingerprint} from the lease bodies —
        the canary's golden-key identity (the same hash the serving
        result cache keys on)."""
        with self._lock:
            return {name: str((d or {}).get("fingerprint") or "")
                    for name, d in self._designs.items()}

    def served_out_keys(self, rid):
        """The out_keys tuple a replica's lease declared it dispatches
        (empty for pre-out_keys leases) — the canary intersects its
        probe keys with this so a probe never 400s on an unserved
        key."""
        with self._lock:
            info = self._replicas.get(rid)
            return tuple(info["out_keys"]) if info else ()

    def owners(self, key):
        with self._lock:
            return self._ring.owners(key)

    def pick(self, key, attempt, exclude=()):
        """The replica for one failover attempt: ring-owner order
        rotated by ``attempt``, skipping excluded and breaker-refusing
        replicas.  None when nobody can take the request."""
        with self._lock:
            cands = self._ring.owners(key)
            n = len(cands)
            for i in range(n):
                rid = cands[(attempt + i) % n]
                if rid in exclude:
                    continue
                br = self._breakers.get(rid)
                if br is None or br.allow():
                    return rid
            return None

    def min_retry_after_s(self):
        """The soonest any breaker would half-open (the 503
        Retry-After hint when every replica is refusing)."""
        with self._lock:
            waits = [br.retry_after_s() for br in self._breakers.values()]
        return min(waits) if waits else 1.0

    # ------------------------------------------------------- breakers

    def record_failure(self, rid, reason):
        metrics.counter("router_upstream_errors").inc()
        with self._lock:
            br = self._breakers.get(rid)
            transition = br.record_failure() if br else None
        if transition == "open":
            metrics.counter("router_breaker_opens").inc()
            log_event("breaker_open", replica=rid,
                      reason=str(reason)[:160],
                      fails=br.fails, cooldown_s=br.cooldown_s)

    def record_success(self, rid, probe=False):
        with self._lock:
            br = self._breakers.get(rid)
            transition = br.record_success() if br else None
        if transition == "close":
            metrics.counter("router_breaker_closes").inc()
            log_event("breaker_close", replica=rid, probe=bool(probe))

    def release_trial(self, rid):
        """Give back a half-open trial slot whose attempt was
        cancelled before it could record an outcome (hedge loser)."""
        with self._lock:
            br = self._breakers.get(rid)
            if br is not None:
                br.release_trial()

    def breaker_states(self):
        with self._lock:
            return {rid: br.state for rid, br in self._breakers.items()}

    def half_open_replicas(self):
        """Replicas whose breaker has cooled down to half-open (the
        prober health-checks these so recovery does not depend on
        client traffic).  Still-open breakers are NOT probed: closing
        one early would bypass the documented cooldown — and /healthz
        answering says nothing about the /evaluate path a hang/5xx
        fault wedged."""
        with self._lock:
            return {rid: self._replicas[rid]
                    for rid, br in self._breakers.items()
                    if br.state == "half_open" and rid in self._replicas}

    def members(self):
        with self._lock:
            return self._ring.members()

    # ------------------------------------------------------ snapshots

    def snapshot(self):
        with self._lock:
            return {
                "n_replicas": len(self._replicas),
                "replicas": {
                    rid: {"addr": info["addr"], "port": info["port"],
                          "designs": sorted(info["designs"]),
                          "breaker": self._breakers[rid].state}
                    for rid, info in sorted(self._replicas.items())},
                "designs": {name: str((d or {}).get("sig") or "")
                            for name, d in sorted(self._designs.items())},
            }

    def ring_view(self):
        """{design name: replica owner order} — the affinity map the
        drill reads to pick its kill target."""
        with self._lock:
            return {name: self._ring.owners(routing_key({"design": name},
                                                        self._designs))
                    for name in sorted(self._designs)}

    def membership_record(self):
        """The ``_fleet/router.json`` record (schema family
        ``router-membership``)."""
        snap = self.snapshot()
        rec = {
            "version": 1,
            "t": time.time(),
            "pid": os.getpid(),
            "n_replicas": snap["n_replicas"],
            "replicas": snap["replicas"],
            "designs": snap["designs"],
        }
        return rec


# ------------------------------------------------------- membership prober


def _http_healthz(addr, port, timeout_s=3.0):
    """Blocking /healthz probe (prober THREAD only, never the event
    loop).  Returns the parsed body or None."""
    conn = http.client.HTTPConnection(addr, int(port), timeout=timeout_s)
    try:
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            return None
        return json.loads(body)
    except (OSError, http.client.HTTPException, ValueError):
        return None
    finally:
        conn.close()


class LedgerProber(threading.Thread):
    """Daemon thread owning all membership IO: lease-ledger scans,
    joiner /healthz confirmation, expired-lease eviction, breaker-open
    recovery probes, and the ``router.json`` publication."""

    def __init__(self, root, state, interval_s=None, probe_http=True):
        super().__init__(name="raft-router-prober", daemon=True)
        self.root = root
        self.state = state
        self.ledger = fleet.FleetLedger(root)
        self.interval_s = float(interval_s if interval_s is not None
                                else config.get("ROUTER_PROBE_S"))
        self.probe_http = bool(probe_http)
        #: joiners that failed their admission /healthz probe this
        #: pass (prober-thread private)
        self._deferred = set()
        #: last published router.json content minus its timestamp
        #: (prober-thread private; gates steady-state republication)
        self._last_published = None
        self._stop_evt = threading.Event()

    def probe_once(self):
        """One membership pass (also called synchronously at startup
        so the router binds with a populated ring)."""
        # evict expired leases first: exactly one evictor wins the
        # rename; a lost race just means another router (or a rescan)
        # already evicted
        for rid, (_rec, age) in self.ledger.expired().items():
            self.ledger.evict(rid, reason="expired", age_s=age)
        live = self.ledger.live()
        if self.probe_http:
            members = set(self.state.members())
            self._deferred = {
                rid for rid, rec in live.items()
                if rid not in members and _http_healthz(
                    rec.get("addr") or "127.0.0.1",
                    rec.get("port") or 0) is None}
            live = {rid: rec for rid, rec in live.items()
                    if rid not in self._deferred}
        added, removed, replaced = self.state.apply_membership(live)
        if added or removed or replaced:
            log_event("router_ring_update", added=added, removed=removed,
                      replaced=replaced, n_replicas=len(live))
            metrics.gauge("router_replicas").set(len(live))
        # breaker recovery without client traffic: a HALF-OPEN replica
        # (cooldown served) that answers /healthz closes via the normal
        # bookkeeping (probe=True on the breaker_close event)
        if self.probe_http:
            for rid, info in self.state.half_open_replicas().items():
                if _http_healthz(info["addr"], info["port"]) is not None:
                    self.state.record_success(rid, probe=True)
        # publish the membership view only when it CHANGED (modulo the
        # timestamp): a steady-state fleet must not rewrite router.json
        # on the shared filesystem every probe period forever
        rec = self.state.membership_record()
        comparable = {k: v for k, v in rec.items() if k != "t"}
        if comparable != self._last_published:
            try:
                fleet.publish_router_record(self.root, rec)
                self._last_published = comparable
            except OSError:
                pass  # the view is advisory; routing state is in memory
        return added, removed

    def run(self):
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.probe_once()
            except Exception:
                pass  # a bad pass must never kill membership

    def stop(self):
        self._stop_evt.set()
        self.join(timeout=2.0)


# ----------------------------------------------------------------- router


class Router:
    """One router instance: membership state + prober + asyncio HTTP
    front end."""

    def __init__(self, root, host="127.0.0.1", port=8788, vnodes=None,
                 probe_http=True):
        self.root = root
        self.host = host
        self.port = int(port)
        self.state = RouterState(vnodes)
        self.prober = LedgerProber(root, self.state,
                                   probe_http=probe_http)
        self.retries = int(config.get("ROUTER_RETRIES"))
        self.backoff_s = float(config.get("ROUTER_BACKOFF_MS")) / 1e3
        self.backoff_cap_s = float(config.get("ROUTER_BACKOFF_CAP_MS")) / 1e3
        self.timeout_s = float(config.get("ROUTER_TIMEOUT_S"))
        self.hedge_s = float(config.get("ROUTER_HEDGE_MS")) / 1e3
        self._server = None
        self._stop = None
        self._handlers = set()
        #: handlers currently processing a request (vs parked on an
        #: idle keep-alive read): shutdown awaits only these
        self._busy = set()
        #: the golden-answer canary daemon (None unless
        #: RAFT_TPU_CANARY_S > 0 — started in start())
        self.canary = None

    # ------------------------------------------------- failover ladder

    async def send_to(self, rid, method, path, body, headers):
        """One upstream attempt under its ``router_upstream`` span."""
        ep = self.state.endpoint(rid)
        if ep is None:
            raise wire.UpstreamError("gone", f"replica {rid} left the ring")
        with span("router_upstream", replica=rid, path=path):
            return await wire.proxy_request(
                ep[0], ep[1], method, path, body, headers,
                timeout_s=self.timeout_s)

    async def failover(self, key, send, sleep=None):
        """The robustness ladder for one request.  ``send(rid)``
        performs one attempt (injectable in tests); returns
        ``(rid, attempts, hedged, status, headers, body)`` or a
        ``(None, attempts, hedged, 503, ...)`` rejection when every
        owning replica is dead or breaker-open."""
        sleep = sleep or asyncio.sleep
        last_reason = "no_replicas"
        last_rid = None
        retry_after = None
        hedged = False
        tried = 0
        for attempt in range(self.retries + 1):
            rid = self.state.pick(key, attempt)
            if rid is None:
                break
            if tried:
                # an upstream Retry-After is THAT replica's window —
                # honor it only when re-trying the same replica; a
                # failover to a different (healthy) one must not
                # inherit the draining replica's stall
                ra = retry_after if rid == last_rid else None
                delay = backoff_delay(tried - 1, base_s=self.backoff_s,
                                      cap_s=self.backoff_cap_s,
                                      retry_after_s=ra)
                metrics.counter("router_retries").inc()
                log_event("router_retry", replica=rid, attempt=tried,
                          reason=last_reason, delay_s=round(delay, 4))
                await sleep(delay)
            tried += 1
            try:
                rid, did_hedge, result = await self._attempt(
                    key, rid, send, first=(attempt == 0))
            except wire.UpstreamError as e:
                last_reason = e.reason
                # the error may have come from the HEDGE replica, not
                # the primary — attribute its Retry-After to whoever
                # actually produced it
                last_rid = getattr(e, "rid", rid)
                retry_after = getattr(e, "retry_after_s", None)
                continue
            hedged = hedged or did_hedge
            status, hdrs, data = result
            return rid, tried, hedged, status, hdrs, data
        if tried == 0 and self.state.owners(key):
            last_reason = "all_breakers_open"
        metrics.counter("router_rejected").inc()
        retry_s = max(retry_after or 0.0, self.state.min_retry_after_s(),
                      1.0)
        log_event("router_reject", reason=last_reason, attempts=tried,
                  retry_after_s=round(retry_s, 3))
        payload = {"ok": False, "reason": last_reason,
                   "error": "no replica available "
                            f"(last failure: {last_reason})",
                   "retry_after_s": round(retry_s, 3)}
        return None, tried, hedged, 503, {}, payload

    async def _attempt(self, key, rid, send, first):
        """One ladder attempt with optional hedging.  Success/failure
        is recorded on the breaker of the replica that actually
        answered; raises :class:`~raft_tpu.serve.wire.UpstreamError`
        when every copy of the attempt failed."""
        if not (first and self.hedge_s > 0):
            return rid, False, await self._classified(rid, send)
        t1 = asyncio.ensure_future(self._classified(rid, send))
        done, _ = await asyncio.wait({t1}, timeout=self.hedge_s)
        if t1 in done:
            # t1 already resolved — this await returns (or raises the
            # classified error) immediately
            return rid, False, await t1
        rid2 = self.state.pick(key, 1, exclude=(rid,))
        if rid2 is None:
            return rid, False, await t1
        metrics.counter("router_hedges").inc()
        log_event("router_hedge", primary=rid, replica=rid2,
                  hedge_ms=self.hedge_s * 1e3)
        t2 = asyncio.ensure_future(self._classified(rid2, send))
        owners = {t1: rid, t2: rid2}
        pending = {t1, t2}
        last_err = None
        while pending:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED)
            for t in done:
                try:
                    result = await t  # resolved — returns/raises now
                except wire.UpstreamError as e:
                    last_err = e
                    continue
                for p in pending:
                    p.cancel()
                    # a loser that FINISHED (with its error) in the
                    # race window must still have its exception
                    # retrieved, or asyncio logs it at gc
                    p.add_done_callback(
                        lambda ft: ft.cancelled() or ft.exception())
                    # a cancelled attempt never reaches its breaker
                    # bookkeeping — give back the half-open trial slot
                    # it may hold, or the breaker refuses traffic until
                    # an external probe clears it
                    self.state.release_trial(owners[p])
                return owners[t], True, result
        raise last_err

    async def _classified(self, rid, send):
        """One attempt + breaker bookkeeping: raises UpstreamError on
        transport failure OR retryable HTTP status (both count against
        the breaker); any other status is a success."""
        try:
            status, hdrs, data = await send(rid)
        except wire.UpstreamError as e:
            self.state.record_failure(rid, e.reason)
            e.rid = rid
            raise
        if status in RETRYABLE_STATUSES:
            self.state.record_failure(rid, f"http_{status}")
            err = wire.UpstreamError(f"http_{status}")
            err.rid = rid
            ra = (hdrs or {}).get("retry-after")
            if ra and str(ra).isdigit():
                err.retry_after_s = float(ra)
            raise err
        self.state.record_success(rid)
        return status, hdrs, data

    # ------------------------------------------------------------ routes

    async def _proxy_evaluate(self, body, headers, client):
        """Route one /evaluate: parse enough of the payload to compute
        the ring key, then run the failover ladder.  The
        ``router_request`` span adopts the client's traceparent and is
        forwarded as the replica's parent — one merged trace covers
        client -> router -> replica -> dispatch."""
        t0 = time.perf_counter()
        try:
            payload = json.loads(body or b"{}")
        except (ValueError, UnicodeDecodeError) as e:
            return 400, {"ok": False, "error": f"bad JSON body: {e}"}, {}
        if not isinstance(payload, dict):
            return 400, {"ok": False,
                         "error": "body must be a JSON object"}, {}
        key = self.state.key_of(payload)
        # boundary="client": the router is the fleet's front door, so
        # an adopted traceparent ALWAYS came from an external client —
        # its parent span legitimately lives in the client's telemetry,
        # and the merge --check orphan rule excuses exactly this (an
        # internally-propagated parent, fabric-style, must still
        # resolve in-capture)
        req_span = span("router_request", endpoint="/evaluate",
                        remote=parse_traceparent(headers.get("traceparent")),
                        boundary="client",
                        client=str(client), key=key[:48])
        with req_span:
            fwd = {k: v for k, v in headers.items()
                   if k in ("x-client", "content-type")}
            # every client must keep its own quota identity at the
            # replicas: without this, anonymous clients collapse into
            # one token bucket keyed on the ROUTER's address
            fwd.setdefault("x-client", str(client))
            tp = format_traceparent(req_span.trace_id, req_span.span_id) \
                if req_span.span_id else headers.get("traceparent")
            if tp:
                fwd["traceparent"] = tp

            async def send(rid):
                return await self.send_to(rid, "POST", "/evaluate", body,
                                          fwd)

            rid, attempts, hedged, status, hdrs, data = \
                await self.failover(key, send)
        wall = time.perf_counter() - t0
        metrics.counter("router_requests").inc()
        # latency exemplar: who answered the p99 route and which trace
        # holds its span tree (attempt/hedge counts tell the failover
        # story without opening the trace)
        exemplar = {"replica": str(rid), "code": int(status),
                    "attempts": int(attempts), "hedged": int(bool(hedged))}
        if req_span.span_id is not None:
            exemplar["trace_id"] = req_span.trace_id
            exemplar["span_id"] = req_span.span_id
        metrics.histogram("router_request_s").observe(wall,
                                                      exemplar=exemplar)
        metrics.window("router_request_window_s").observe(wall,
                                                          exemplar=exemplar)
        prov = (hdrs.get("x-raft-provenance")
                if isinstance(hdrs, dict) else None)
        log_event("router_request", replica=rid, code=int(status),
                  attempts=attempts, hedged=bool(hedged),
                  design=str(payload.get("design") or "inline"),
                  wall_s=round(wall, 6), provenance=prov)
        extra = {}
        if isinstance(hdrs, dict) and hdrs.get("traceparent"):
            extra["traceparent"] = hdrs["traceparent"]
        if prov:
            # forward the replica's provenance stamp verbatim: the
            # client sees WHAT produced its numbers even through the
            # failover front (serve/client.py parses it into
            # last_provenance)
            extra["x-raft-provenance"] = prov
        if rid is not None:
            # which replica answered — the affinity drill reads this
            extra["x-raft-replica"] = str(rid)
        if rid is None:
            extra["Retry-After"] = str(
                max(1, int(float(data.get("retry_after_s") or 0)) + 1))
            return status, data, extra
        if isinstance(data, (bytes, bytearray)):
            try:
                data = json.loads(data)
            except ValueError:
                data = data.decode(errors="replace")
        return status, data, extra

    def _healthz(self):
        snap = self.state.snapshot()
        counters = {c: metrics.counter(c).value for c in
                    ("router_requests", "router_retries", "router_hedges",
                     "router_breaker_opens", "router_breaker_closes",
                     "router_rejected", "router_upstream_errors")}
        win = metrics.window("router_request_window_s").snapshot(
            float(config.get("SERVE_WINDOW_S")))
        return 200, {"ok": True,
                     "uptime_s": round(time.perf_counter() - _T0, 3),
                     "fleet_dir": self.root,
                     "window": win,
                     **snap, **counters}

    async def _route(self, method, path, body, headers, client,
                     peer_host="?"):
        if path == "/evaluate":
            if method != "POST":
                return 405, {"ok": False, "error": "POST required"}, {}
            return await self._proxy_evaluate(body, headers, client)
        if method != "GET":
            return 405, {"ok": False, "error": "GET required"}, {}
        if path == "/healthz":
            status, payload = self._healthz()
            return status, payload, {}
        if path == "/alerts":
            # live alert-engine state + the router canary's golden/
            # parity summary — in-memory reads only, loop-safe
            from raft_tpu.obs import alerts as alerts_mod

            payload = alerts_mod.endpoint_payload()
            payload["canary"] = (self.canary.canary.summary()
                                 if self.canary is not None else None)
            return 200, payload, {}
        if path == "/ring":
            return 200, {"ok": True, "ring": self.state.ring_view()}, {}
        if path == "/designs":
            snap = self.state.snapshot()
            return 200, {"ok": True,
                         "designs": sorted(snap["designs"])}, {}
        if path == "/metrics":
            return 200, metrics.to_prometheus(), {}
        if path == "/debug/flight":
            # the router's black box, loopback-only like the replica's:
            # serialize the live ring without touching disk
            if peer_host not in wire.LOOPBACK_HOSTS:
                return 403, {"ok": False,
                             "error": "/debug/flight is loopback-only"}, {}
            from raft_tpu.obs import flight

            return 200, flight.serialize_text(trigger="debug"), {}
        return 404, {"ok": False, "error": f"no route {path}"}, {}

    # -------------------------------------------------------- connection

    async def _handle(self, reader, writer):
        task = asyncio.current_task()
        self._handlers.add(task)
        peer = writer.get_extra_info("peername")
        peer_host = peer[0] if isinstance(peer, tuple) else "?"
        try:
            while True:
                try:
                    req = await wire.read_request(reader)
                except (ValueError, asyncio.IncompleteReadError) as e:
                    writer.write(wire.response_bytes(
                        400, {"ok": False, "error": str(e)[:200]}, False))
                    await writer.drain()
                    break
                if req is None:
                    break
                method, path, headers, body = req
                client = headers.get("x-client") or peer_host
                self._busy.add(task)
                try:
                    try:
                        status, payload, extra = await self._route(
                            method, path, body, headers, client,
                            peer_host=peer_host)
                    except Exception as e:  # noqa: BLE001 — keep routing
                        status, payload, extra = 500, {
                            "ok": False, "error": repr(e)[:300]}, {}
                    keep = (headers.get("connection",
                                        "keep-alive").lower() != "close") \
                        and not (self._stop is not None
                                 and self._stop.is_set())
                    writer.write(wire.response_bytes(status, payload,
                                                     keep, extra))
                    await writer.drain()
                finally:
                    self._busy.discard(task)
                metrics.counter("router_http_requests").inc()
                if not keep:
                    break
        finally:
            self._handlers.discard(task)
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    # ------------------------------------------------------------- serve

    async def start(self):
        loop = asyncio.get_running_loop()
        # arm the flight recorder's flusher/crash hooks (no-op without
        # RAFT_TPU_FLIGHT_DIR): a SIGKILLed router leaves a black box
        from raft_tpu.obs import flight

        flight.maybe_start()
        # populate the ring BEFORE binding: the first client request
        # must never race an empty membership (ledger IO — executor)
        await loop.run_in_executor(None, self.prober.probe_once)
        self.prober.start()
        if float(config.get("CANARY_S") or 0) > 0:
            # golden-answer canary: low-rate probes pinned per replica,
            # compared bit-for-status / tolerance-for-floats against
            # content-addressed goldens + cross-replica provenance
            # consistency (raft_tpu.serve.canary); blocking probe IO
            # lives on ITS thread, like the membership prober
            from raft_tpu.serve.canary import RouterCanary

            self.canary = RouterCanary(self.state)
            self.canary.start()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._stop = asyncio.Event()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, self._stop.set)
            except (NotImplementedError, RuntimeError):
                pass
        snap = self.state.snapshot()
        log_event("router_start", host=self.host, port=self.port,
                  fleet_dir=self.root, n_replicas=snap["n_replicas"],
                  replicas=sorted(snap["replicas"]))
        return self

    async def serve_until_stopped(self):
        await self._stop.wait()
        await self.shutdown()

    async def shutdown(self):
        """Stop accepting, let in-flight proxied requests finish, stop
        the prober."""
        loop = asyncio.get_running_loop()
        self._server.close()
        # await only handlers MID-REQUEST; ones parked on an idle
        # keep-alive read would hold the drain window for nothing —
        # cancel those immediately
        for t in list(self._handlers - self._busy):
            t.cancel()
        busy = {t for t in self._busy if not t.done()}
        if busy:
            await asyncio.wait(busy,
                               timeout=float(config.get("SERVE_DRAIN_S")))
        for t in list(self._handlers):
            t.cancel()
        await self._server.wait_closed()
        await loop.run_in_executor(None, self.prober.stop)
        if self.canary is not None:
            await loop.run_in_executor(None, self.canary.stop)
        path = config.get("METRICS")
        if path:
            await loop.run_in_executor(None, metrics.export, path)
        # append the session's run record (RAFT_TPU_RUNS_DIR): the
        # router's registry at shutdown carries the fleet's routing
        # story — request/retry/hedge/breaker counters, the sliding
        # latency window, canary pass/fail — so `obs runs regress`
        # sees router sessions too (replicas already record theirs in
        # serve/http.py).  Executor: file IO + a `git rev-parse`
        # subprocess (obs.runs.git_sha)
        from raft_tpu.obs import runs as obs_runs

        wall_s = time.perf_counter() - _T0
        requests = metrics.counter("router_requests").value
        await loop.run_in_executor(
            None, lambda: obs_runs.maybe_record(
                "router", wall_s=wall_s, extra={"requests": requests}))
        log_event("router_stop",
                  requests=metrics.counter("router_requests").value,
                  retries=metrics.counter("router_retries").value)


async def run_router(root, host="127.0.0.1", port=8788, ready=None):
    """Start + block until signalled (the ``router`` CLI entry)."""
    router = await Router(root, host, port).start()
    if ready is not None:
        ready(router)
    await router.serve_until_stopped()
    return router
