"""Unit tests for the math kernel layer (raft_tpu.ops).

Expected values are computed with independent straight-line numpy
implementations of the underlying physics formulas (frustum integrals by
numerical quadrature, transforms by explicit cross products), plus spot
values mirroring the reference's own unit checks
(/root/reference/tests/test_helpers.py).
"""

import numpy as np
import jax.numpy as jnp
from numpy.testing import assert_allclose

from raft_tpu.ops import transforms as tf
from raft_tpu.ops import frustum as fr
from raft_tpu.ops import waves as wv


# ---------------------------------------------------------------- transforms

def test_skew_is_cross():
    rng = np.random.default_rng(0)
    r = rng.normal(size=3)
    v = rng.normal(size=3)
    assert_allclose(np.asarray(tf.skew(r)) @ v, np.cross(v, r), rtol=1e-12)


def test_rotation_matrix_axes():
    # yaw by 90 deg about z maps x->y
    R = np.asarray(tf.rotation_matrix(0.0, 0.0, np.pi / 2))
    assert_allclose(R @ np.array([1.0, 0, 0]), [0, 1, 0], atol=1e-12)
    # pitch by 90 deg about y maps x->-z
    R = np.asarray(tf.rotation_matrix(0.0, np.pi / 2, 0.0))
    assert_allclose(R @ np.array([1.0, 0, 0]), [0, 0, -1], atol=1e-12)
    # orthonormality for random angles
    R = np.asarray(tf.rotation_matrix(0.3, -0.7, 1.1))
    assert_allclose(R @ R.T, np.eye(3), atol=1e-12)


def test_translate_force():
    F = np.array([1.0, 2.0, 3.0])
    r = np.array([4.0, 5.0, 6.0])
    out = np.asarray(tf.translate_force_3to6(F, r))
    assert_allclose(out[:3], F)
    assert_allclose(out[3:], np.cross(r, F))


def test_translate_matrix_6to6_equiv_T():
    # T^T M T with rigid-kinematics T = [[I, H(r)],[0, I]] must equal the
    # closed-form translation (raft equivalence used for DOF reduction).
    rng = np.random.default_rng(1)
    A = rng.normal(size=(6, 6))
    M = A + A.T
    r = rng.normal(size=3)
    H = np.asarray(tf.skew(r))
    T = np.block([[np.eye(3), H], [np.zeros((3, 3)), np.eye(3)]])
    assert_allclose(np.asarray(tf.translate_matrix_6to6(M, r)), T.T @ M @ T, atol=1e-12)


def test_translate_matrix_3to6_consistent():
    rng = np.random.default_rng(2)
    m = rng.normal(size=(3, 3))
    m = m + m.T
    r = rng.normal(size=3)
    M6 = np.zeros((6, 6))
    M6[:3, :3] = m
    assert_allclose(
        np.asarray(tf.translate_matrix_3to6(m, r)),
        np.asarray(tf.translate_matrix_6to6(M6, r)),
        atol=1e-12,
    )


def test_rotate_matrix_6():
    rng = np.random.default_rng(3)
    A = rng.normal(size=(6, 6))
    M = A + A.T
    R = np.asarray(tf.rotation_matrix(0.2, 0.5, -0.4))
    out = np.asarray(tf.rotate_matrix_6(M, R))
    assert_allclose(out[:3, :3], R @ M[:3, :3] @ R.T, atol=1e-12)
    assert_allclose(out[3:, 3:], R @ M[3:, 3:] @ R.T, atol=1e-12)
    assert_allclose(out[:3, 3:], R @ M[:3, 3:] @ R.T, atol=1e-12)
    # note the reference symmetrises the off-diagonal block: J'^T ends up
    # as (R J R^T)^T which our blockwise version reproduces only for
    # symmetric M — matching the reference's use (inertia tensors).


def test_weight_of_point_mass():
    W, C = tf.weight_of_point_mass(100.0, np.array([1.0, 2.0, 3.0]), g=9.81)
    W, C = np.asarray(W), np.asarray(C)
    assert_allclose(W[:3], [0, 0, -981.0])
    assert_allclose(W[3:], np.cross([1.0, 2.0, 3.0], [0, 0, -981.0]))
    assert_allclose(C[3, 3], -100 * 9.81 * 3.0)
    assert_allclose(C[4, 4], -100 * 9.81 * 3.0)


# ------------------------------------------------------------------ frustum

def _quad_frustum(dA, dB, H, n=200000):
    """Trapezoid-quadrature reference for circular frustum V/hc/MoI."""
    z = np.linspace(0, H, n)
    d = dA + (dB - dA) * z / H
    A = 0.25 * np.pi * d**2
    V = np.trapezoid(A, z)
    hc = np.trapezoid(A * z, z) / V
    I_ax = np.trapezoid(0.5 * A * (d / 2) ** 2, z)  # rho=1
    I_rad = np.trapezoid(A * (0.25 * (d / 2) ** 2 + z**2), z)
    return V, hc, I_rad, I_ax


def test_frustum_circ_against_quadrature():
    for dA, dB, H in [(5.0, 5.0, 10.0), (5.0, 3.0, 7.0), (2.0, 6.0, 4.0)]:
        V, hc = fr.frustum_vcv_circ(dA, dB, H)
        Ir, Ia = fr.frustum_moi_circ(dA, dB, H, 1.0)
        Vq, hcq, Irq, Iaq = _quad_frustum(dA, dB, H)
        assert_allclose(float(V), Vq, rtol=1e-6)
        assert_allclose(float(hc), hcq, rtol=1e-6)
        assert_allclose(float(Ir), Irq, rtol=1e-6)
        assert_allclose(float(Ia), Iaq, rtol=1e-6)


def test_frustum_zero_height():
    V, hc = fr.frustum_vcv_circ(3.0, 3.0, 0.0)
    assert float(V) == 0.0
    Ir, Ia = fr.frustum_moi_circ(3.0, 3.0, 0.0, 1000.0)
    assert float(Ir) == 0.0 and float(Ia) == 0.0


def test_frustum_rect_cuboid():
    sl = np.array([2.0, 3.0])
    V, hc = fr.frustum_vcv_rect(sl, sl, 4.0)
    assert_allclose(float(V), 2 * 3 * 4)
    assert_allclose(float(hc), 2.0)
    Ixx, Iyy, Izz = fr.frustum_moi_rect(sl, sl, 4.0, 1.0)
    M = 24.0
    assert_allclose(float(Ixx), M / 12 * (3**2 + 4 * 4**2), rtol=1e-12)
    assert_allclose(float(Iyy), M / 12 * (2**2 + 4 * 4**2), rtol=1e-12)
    assert_allclose(float(Izz), M / 12 * (2**2 + 3**2), rtol=1e-12)


def test_frustum_rect_tapered_vs_quadrature():
    slA = np.array([2.0, 3.0])
    slB = np.array([4.0, 1.5])
    H = 5.0
    n = 400000
    z = np.linspace(0, H, n)
    L = slA[0] + (slB[0] - slA[0]) * z / H
    W = slA[1] + (slB[1] - slA[1]) * z / H
    A = L * W
    Vq = np.trapezoid(A, z)
    Ixxq = np.trapezoid(A * (W**2 / 12 + z**2), z)
    Iyyq = np.trapezoid(A * (L**2 / 12 + z**2), z)
    Izzq = np.trapezoid(A * (L**2 + W**2) / 12, z)
    V, hc = fr.frustum_vcv_rect(slA, slB, H)
    Ixx, Iyy, Izz = fr.frustum_moi_rect(slA, slB, H, 1.0)
    # note: reference V formula uses sqrt(A1 A2) mid-area (prismatoid
    # approximation) — only exact for proportional taper, so compare MoI
    # (exact closed forms) tightly and V loosely.
    assert_allclose(float(Ixx), Ixxq, rtol=1e-5)
    assert_allclose(float(Iyy), Iyyq, rtol=1e-5)
    assert_allclose(float(Izz), Izzq, rtol=1e-5)


# -------------------------------------------------------------------- waves

def test_wave_number_satisfies_dispersion():
    g = 9.81
    for h in [20.0, 320.0, 4000.0]:
        w = np.linspace(0.02, 6.0, 50)
        k = np.asarray(wv.wave_number(w, h, g=g))
        assert_allclose(g * k * np.tanh(k * h), w**2, rtol=1e-10)


def test_jonswap_matches_reference_formula():
    ws = np.linspace(0.03, 2.0, 100)
    Hs, Tp = 6.0, 12.0
    S = np.asarray(wv.jonswap(ws, Hs, Tp))
    # independent evaluation (IEC 61400-3 formula as in helpers.py:703-760)
    TpOvrSqrtHs = Tp / np.sqrt(Hs)
    if TpOvrSqrtHs <= 3.6:
        Gamma = 5.0
    elif TpOvrSqrtHs >= 5.0:
        Gamma = 1.0
    else:
        Gamma = np.exp(5.75 - 1.15 * TpOvrSqrtHs)
    f = 0.5 / np.pi * ws
    fpOvrf4 = (Tp * f) ** -4.0
    C = 1.0 - 0.287 * np.log(Gamma)
    Sigma = np.where(f <= 1.0 / Tp, 0.07, 0.09)
    Alpha = np.exp(-0.5 * ((f * Tp - 1.0) / Sigma) ** 2)
    S_ref = 0.5 / np.pi * C * 0.3125 * Hs * Hs * fpOvrf4 / f * np.exp(-1.25 * fpOvrf4) * Gamma**Alpha
    assert_allclose(S, S_ref, rtol=1e-12)
    # explicit gamma value: positive where not underflowed, peak near wp
    S1 = np.asarray(wv.jonswap(ws, Hs, Tp, gamma=1.0))
    assert np.all(S1 >= 0) and S1[np.argmin(np.abs(ws - 2 * np.pi / Tp))] > 0


def test_wave_kinematics_deep_water_limit():
    # In deep water at the surface, |u| = w * zeta and p = rho g zeta.
    g, rho = 9.81, 1025.0
    h = 4000.0
    w = np.array([0.8])
    k = np.asarray(wv.wave_number(w, h))
    zeta0 = np.ones(1, dtype=complex)
    r = np.array([0.0, 0.0, -1e-6])
    u, ud, p = wv.wave_kinematics(zeta0, 0.0, w, k, h, r, rho=rho, g=g)
    assert_allclose(np.abs(np.asarray(u)[0, 0]), w[0], rtol=1e-4)
    assert_allclose(np.abs(np.asarray(p)[0]), rho * g, rtol=1e-4)
    # decay with depth: u(z) = u(0) exp(k z)
    r2 = np.array([0.0, 0.0, -50.0])
    u2, _, _ = wv.wave_kinematics(zeta0, 0.0, w, k, h, r2, rho=rho, g=g)
    assert_allclose(
        np.abs(np.asarray(u2)[0, 0]), w[0] * np.exp(k[0] * -50.0), rtol=1e-4
    )


def test_wave_kinematics_above_water_zero():
    h = 100.0
    w = np.array([0.5, 1.0])
    k = np.asarray(wv.wave_number(w, h))
    u, ud, p = wv.wave_kinematics(np.ones(2, dtype=complex), 0.3, w, k, h,
                                  np.array([1.0, 2.0, 5.0]))
    assert np.all(np.asarray(u) == 0)
    assert np.all(np.asarray(p) == 0)


def test_wave_kinematics_phase_shift():
    # phase at x relative to origin is exp(-i k x cos(beta))
    h = 320.0
    w = np.array([0.7])
    k = np.asarray(wv.wave_number(w, h))
    z = np.array([0.0, 0.0, -10.0])
    x = np.array([25.0, 0.0, -10.0])
    u0, _, _ = wv.wave_kinematics(np.ones(1, dtype=complex), 0.0, w, k, h, z)
    u1, _, _ = wv.wave_kinematics(np.ones(1, dtype=complex), 0.0, w, k, h, x)
    assert_allclose(
        np.asarray(u1)[0, 0] / np.asarray(u0)[0, 0],
        np.exp(-1j * k[0] * 25.0),
        rtol=1e-10,
    )


def test_get_kinematics():
    w = np.array([0.5, 1.0])
    Xi = np.zeros((6, 2), dtype=complex)
    Xi[0, :] = 1.0      # unit surge
    Xi[4, :] = 0.1      # pitch
    r = np.array([0.0, 0.0, 10.0])
    dr, v, a = wv.get_kinematics(r, Xi, w)
    dr = np.asarray(dr)
    # surge + pitch*z lever: dx = 1 + 0.1*10
    assert_allclose(dr[0], [2.0, 2.0], rtol=1e-12)
    assert_allclose(np.asarray(v)[0], 1j * w * 2.0, rtol=1e-12)
    assert_allclose(np.asarray(a)[0], -(w**2) * 2.0, rtol=1e-12)


def test_rms_psd_rao():
    xi = np.array([3 + 4j, 0.0, 1.0])
    assert_allclose(float(wv.get_rms(xi)), np.sqrt(0.5 * (25 + 1)))
    assert_allclose(np.asarray(wv.get_psd(xi, 0.1)), 0.5 * np.abs(xi) ** 2 / 0.1)
    zeta = np.array([2.0, 0.0, 4.0])
    rao = np.asarray(wv.get_rao(xi, zeta))
    assert_allclose(rao, [1.5 + 2j, 0.0, 0.25])


def test_mcf_cm_table_accuracy():
    """The cubic-Hermite MacCamy-Fuchs table matches the exact Hankel
    form to ~1e-11 on the ramp-blended quantity over the full range
    (morison.py docstring claim), and the jax path equals the numpy
    path bit-for-bit (build/trace consistency)."""
    import jax.numpy as jnp
    from scipy.special import hankel1

    from raft_tpu.physics.morison import mcf_blend, mcf_cm

    rng = np.random.default_rng(0)
    x = rng.uniform(1e-4, 80.0, 20000)
    with np.errstate(all="ignore"):
        Hp1 = 0.5 * (hankel1(0, x) - hankel1(2, x))
        exact = 4j / (np.pi * x**2 * Hp1)
    ramp = np.where(x < np.pi / 5, 0.5 * (1 - np.cos(5 * x)), 1.0)
    bl_exact = exact * ramp + 2.0 * (1 - ramp)
    bl_got, _ = mcf_blend(x, 2.0, 2.0)
    rel = np.abs(bl_got - bl_exact) / np.abs(bl_exact)
    assert rel.max() < 1e-10

    got_np = mcf_cm(x)
    got_j = np.asarray(mcf_cm(jnp.asarray(x)))
    assert np.array_equal(got_j, got_np)


def test_structlog_events(tmp_path, monkeypatch):
    """Structured JSONL logging (SURVEY §5.1): stage timing and events
    are emitted as one JSON object per line when RAFT_TPU_LOG is set,
    the sink follows mid-process env-var changes (no import-time
    latching), and the module is a strict no-op otherwise."""
    import raft_tpu.utils.structlog as sl
    from _obs_helpers import read_events

    dest = tmp_path / "log.jsonl"
    monkeypatch.setenv("RAFT_TPU_LOG", str(dest))
    with sl.stage("unit_stage", case=3):
        pass
    sl.log_event("custom", resid=1.5e-3, converged=True)
    # every sink opens with the proc_start clock anchor (PR 10: the
    # `obs trace --merge` cross-process timeline needs unix_t <-> t)
    (anchor,) = read_events(dest, skip_anchor=False, name="proc_start")
    assert anchor["unix_t"] > 1e9
    stage_ev, custom = read_events(dest)  # anchor skipped by default
    assert stage_ev["event"] == "unit_stage"
    assert stage_ev["ok"] is True and stage_ev["case"] == 3
    assert stage_ev["wall_s"] >= 0
    # every record carries the pid/run_id telemetry stamps (PR 5)
    import os as _os

    assert custom == {"t": custom["t"], "event": "custom",
                      "pid": _os.getpid(), "run_id": custom["run_id"],
                      "resid": 1.5e-3, "converged": True}
    assert stage_ev["run_id"] == custom["run_id"]

    # retargeting mid-process takes effect without a module reload
    # (the fresh sink gets its own anchor)
    dest2 = tmp_path / "log2.jsonl"
    monkeypatch.setenv("RAFT_TPU_LOG", str(dest2))
    sl.log_event("retargeted")
    assert read_events(dest2, skip_anchor=False,
                       name="proc_start")  # fresh sink, fresh anchor
    (ev,) = read_events(dest2)
    assert ev["event"] == "retargeted"

    monkeypatch.delenv("RAFT_TPU_LOG")
    assert not sl.enabled()
    sl.log_event("dropped")  # no sink, no error
