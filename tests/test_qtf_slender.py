"""Slender-body QTF parity vs reference golden values.

Mirrors test_calcQTF_slenderBody (/root/reference/tests/test_fowt.py:
192-216): fixed-body QTFs for the designs with potSecOrder == 1,
compared at the reference's tolerance (rtol 1e-5, atol 1e-3).
"""

import os
import pickle

import numpy as np
import pytest
from numpy.testing import assert_allclose

from tests.conftest import ref_data

import raft_tpu
from raft_tpu.physics.qtf_slender import fowt_qtf_slender

pytestmark = pytest.mark.slow

DESIGNS = ["VolturnUS-S.yaml", "VolturnUS-S-pointInertia.yaml"]


@pytest.mark.parametrize("design", DESIGNS, ids=[d.split(".")[0] for d in DESIGNS])
def test_qtf_slender_fixed_body(design):
    path = ref_data(design)
    golden = path.replace(".yaml", "_true_calcQTF_slenderBody.pkl")
    if not (os.path.exists(path) and os.path.exists(golden)):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    assert model.fowtList[0].potSecOrder == 1
    fh = model.hydro[0]
    fh.hydro_excitation({"wave_heading": 30, "wave_period": 12, "wave_height": 6})
    qtf = fowt_qtf_slender(model, 0, Xi0=None)
    with open(golden, "rb") as f:
        true = pickle.load(f)
    assert_allclose(qtf, np.asarray(true["qtf"]), rtol=1e-5, atol=1e-3)


def test_second_order_in_dynamics():
    """potSecOrder==1 end-to-end: 2nd-order forces enter the response."""
    path = ref_data("VolturnUS-S.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    case = {"wind_speed": 0, "wind_heading": 0, "turbulence": 0,
            "turbine_status": "idle", "yaw_misalign": 0,
            "wave_spectrum": "JONSWAP", "wave_period": 12, "wave_height": 6,
            "wave_heading": 0, "current_speed": 0, "current_heading": 0}
    Xi, info = model.solve_dynamics(case)
    assert np.isfinite(np.asarray(Xi)).all()
    # mean drift force present and pushing downwave
    assert model._last_drift_mean[0, 0] > 0
