"""WAMIT interchange round-trips: .12d QTF writer, .4 RAO writer/reader,
.p2 reader, .gdf mesh writer/reader.

The reference uses these files as its checkpoint format for expensive
2nd-order results (writeQTF raft_fowt.py:2131-2156, the .4 RAO debug
output :2027-2041, readWAMIT_p2 helpers.py:1434-1469, GDF writers
member2pnl.py:314/672/847) — round-tripping through our writers/readers
pins both directions at once.
"""

import numpy as np
import pytest

from raft_tpu.io.panels import read_gdf, write_gdf
from raft_tpu.io.wamit import read_rao_4, read_wamit_p2, write_rao_4
from raft_tpu.physics.secondorder import read_qtf_12d, write_qtf_12d

RNG = np.random.default_rng(7)


def test_qtf_12d_roundtrip(tmp_path):
    nw, nh, ndof = 5, 2, 6
    w = np.linspace(0.05, 0.45, nw)
    heads = np.deg2rad(np.array([0.0, 30.0]))
    # hermitian in (w1, w2): Q(w2,w1) = conj(Q(w1,w2))
    qtf = (RNG.normal(size=(nw, nw, nh, ndof))
           + 1j * RNG.normal(size=(nw, nw, nh, ndof))) * 1e6
    for ih in range(nh):
        for idof in range(ndof):
            m = qtf[:, :, ih, idof]
            qtf[:, :, ih, idof] = np.triu(m) + np.triu(m, 1).conj().T

    p = tmp_path / "test.12d"
    write_qtf_12d(p, qtf, w, heads)
    back = read_qtf_12d(p)
    np.testing.assert_allclose(back["w_2nd"], w, rtol=1e-5)
    np.testing.assert_allclose(back["heads_rad"], heads, atol=1e-6)
    np.testing.assert_allclose(back["qtf"], qtf, rtol=2e-5,
                               atol=1e-5 * np.abs(qtf).max())


def test_rao_4_roundtrip(tmp_path):
    nw = 8
    w = np.linspace(0.1, 1.5, nw)
    Xi = RNG.normal(size=(6, nw)) + 1j * RNG.normal(size=(6, nw))
    p = tmp_path / "test.4"
    write_rao_4(p, w, Xi, beta_deg=45.0)
    wb, heads, Xib = read_rao_4(p)
    np.testing.assert_allclose(wb, w, rtol=1e-5)
    assert heads.tolist() == [45.0]
    np.testing.assert_allclose(Xib[0], Xi, rtol=2e-5, atol=1e-6)


def test_p2_reader(tmp_path):
    """.p2 rows [period, head, DoF, |F|, phase, Re, Im] -> per-DOF
    (n_period, n_heading) matrices with rho g ULEN^k dimensionalisation
    (k = 2 forces, 3 moments)."""
    periods = [6.0, 8.0]
    heads = [0.0, 90.0]
    rows = []
    vals = {}
    v = 1.0
    for T in periods:
        for h in heads:
            for dof in range(1, 7):
                re, im = v, -0.5 * v
                vals[(T, h, dof)] = re + 1j * im
                rows.append(f"{T} {h} {dof} {abs(re + 1j * im)} 0.0 {re} {im}")
                v += 1.0
    p = tmp_path / "test.p2"
    p.write_text("\n".join(rows) + "\n")

    out = read_wamit_p2(p, rho=1025.0, ulen=2.0, g=9.81)
    np.testing.assert_allclose(out["period"], periods)
    np.testing.assert_allclose(out["heading"], heads)
    names = ["surge", "sway", "heave", "roll", "pitch", "yaw"]
    for idof, name in enumerate(names):
        k = 3 if idof >= 3 else 2
        fac = 1025.0 * 9.81 * 2.0 ** k
        for iT, T in enumerate(periods):
            for ih, h in enumerate(heads):
                assert out[name][iT, ih] == pytest.approx(
                    vals[(T, h, idof + 1)] * fac), (name, T, h)


def test_gdf_roundtrip(tmp_path):
    from raft_tpu.io.panels import mesh_cylinder

    verts, cents, norms, areas = mesh_cylinder(
        stations=[0.0, 10.0], diameters=[6.0, 6.0],
        rA=np.array([0.0, 0.0, -10.0]), q=np.array([0.0, 0.0, 1.0]),
        n_az=8, dz_max=2.5)
    p = tmp_path / "mesh.gdf"
    write_gdf(p, verts)
    vb, cb, nb, ab = read_gdf(p)
    assert vb.shape == verts.shape
    np.testing.assert_allclose(vb, np.asarray(verts), atol=6e-4)
    np.testing.assert_allclose(ab, np.asarray(areas), rtol=1e-2)


def test_gdf_clip_above_water(tmp_path):
    quads = np.array([
        # fully above water: dropped
        [[0, 0, 1], [1, 0, 1], [1, 1, 2], [0, 1, 2]],
        # straddling: kept, z clamped to 0
        [[0, 0, -1], [1, 0, -1], [1, 0, 1], [0, 0, 1]],
    ], dtype=float)
    p = tmp_path / "clip.gdf"
    write_gdf(p, quads, clip_above_water=True)
    vb, *_ = read_gdf(p)
    assert len(vb) == 1
    assert vb[:, :, 2].max() <= 0.0
