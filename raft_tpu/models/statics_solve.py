"""Mean-offset (static equilibrium) solve for a FOWT.

Equivalent of ``Model.solveStatics`` (``/root/reference/raft/
raft_model.py:550-964``) with the linearised-hydrostatics approach
(staticsMod=0) and constant environmental forcing (forcingsMod=0):

    F(X) = F_undisplaced - K_hydrostatic X + F_env + F_moor(X)
    K(X) = K_hydrostatic + C_elast + C_moor(X)
    X   <- X + K^{-1} F          (damped Newton)

The mooring reaction and its exact tangent stiffness come from the jax
catenary module, so the iteration is a clean Newton method (the
reference's ad-hoc diagonal-inflation fallbacks, raft_model.py:847-878,
are unnecessary).  The loop is a ``lax.while_loop`` so the whole
equilibrium solve jits and vmaps over load cases and designs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.physics.mooring import mooring_force, mooring_stiffness


def solve_equilibrium(
    fs,
    ms,
    K_hydrostatic,
    F_undisplaced,
    F_env,
    C_elast=None,
    X0=None,
    max_iter=30,
    tol="reference",
    step_cap=None,
):
    """Newton solve for the mean platform offsets X (nDOF,).

    Parameters mirror the reference's solveStatics assembly: constant
    hydrostatic stiffness + forces (raft_model.py:605-607), constant
    environment forces (:611-630), pose-dependent mooring (:747).

    step_cap: per-DOF max |dX| per iteration (defaults to the
    reference's 30 m / 5 m / 0.1 rad caps, raft_model.py:666-667).

    tol: scalar for a fully-converged solve, or the string
    "reference" to reproduce the reference's stopping semantics
    (per-DOF tolerances 0.05 m / 0.005 rad, raft_model.py:658-664,
    with the sub-tolerance Newton step *discarded* — dsolve2 checks
    convergence before applying the step).  The reference's published
    equilibria correspond to that rule, so it is the default.
    """
    nDOF = fs.nDOF
    if X0 is None:
        X0 = jnp.zeros(nDOF)
    if C_elast is None:
        C_elast = jnp.zeros((nDOF, nDOF))
    if step_cap is None:
        caps = []
        for dof in fs.reducedDOF:
            caps.append(30.0 if dof[1] < 2 else 5.0 if dof[1] == 2 else 0.1)
        step_cap = jnp.asarray(caps)
    if isinstance(tol, str) and tol == "reference":
        tols = []
        for dof in fs.reducedDOF:
            tols.append(0.05 if dof[1] < 3 else 0.005)
        tol_vec = jnp.asarray(tols)
    else:
        tol_vec = jnp.full(nDOF, tol)

    def net_force(X):
        F = F_undisplaced - K_hydrostatic @ X + F_env
        if ms is not None:
            Fm, _ = mooring_force(ms, X[:6])
            F = F.at[:6].add(Fm)
        F = F - C_elast @ X
        return F

    def step(X):
        F = net_force(X)
        K = K_hydrostatic + C_elast
        if ms is not None:
            K = K.at[:6, :6].add(mooring_stiffness(ms, X[:6]))
        dX = jnp.linalg.solve(K, F)
        return jnp.clip(dX, -step_cap, step_cap)

    def body(carry):
        X, it, _ = carry
        dX = step(X)
        done = jnp.all(jnp.abs(dX) < tol_vec)
        X = jnp.where(done, X, X + dX)  # sub-tolerance step is discarded
        return X, it + 1, done

    def cond(carry):
        _, it, done = carry
        return (it < max_iter) & (~done)

    X, _, _ = jax.lax.while_loop(cond, body, (X0, 0, jnp.asarray(False)))
    return X, net_force(X)
