"""Trace-hygiene static analysis for the raft_tpu codebase.

Three engines, one goal: the invariants that keep the per-ω impedance
solve vmappable, compile-stable and dtype-tight are *machine-checked*
instead of re-discovered as silent 10x slowdowns on a pod.

* :mod:`raft_tpu.analysis.lint` — a custom AST linter for the bug
  classes PR 2 fixed by hand: hard-coded complex/float64 dtype
  literals in traced modules, host-Python coercions of traced values,
  raw ``RAFT_TPU_*`` env reads outside the
  :mod:`raft_tpu.utils.config` registry, and ``jax.jit`` call sites
  missing ``static_argnames`` for config-like arguments.
* :mod:`raft_tpu.analysis.jaxpr_contracts` — declarative contracts
  checked against the *traced* jaxprs of the public entry points on
  the bundled spar design: no geometry re-gathers inside the drag
  fixed-point body, no host callbacks in hot paths, no 64-bit avals
  under ``RAFT_TPU_DTYPE=float32``, and per-entry-point
  primitive-count budgets against a checked-in baseline.
* :mod:`raft_tpu.analysis.recompile` — a recompilation sentinel that
  counts XLA backend compiles across repeated driver/sweep
  invocations (second identical run must trigger zero).
* :mod:`raft_tpu.analysis.concurrency` — concurrency invariants of
  the multi-process runtime (PRs 8-11): atomic ledger/store writes,
  a non-blocking serve event loop (taint-based), lock discipline over
  the annotated shared registries, and thread hygiene
  (daemon/name/stop-join) for every background sampler.
* :mod:`raft_tpu.analysis.schemas` — cross-process writer/reader
  schema contracts: the key sets of every record family (leases, done
  records, worker status, fabric/manifest/quarantine JSON, run
  records, AOT sidecars) extracted statically from their write/read
  sites and pinned against ``analysis/schema_baseline.json``.

CLI: ``python -m raft_tpu.analysis
{lint,concurrency,schemas,contracts,baseline,flags}``.
"""

from raft_tpu.analysis.lint import Finding, lint_paths  # noqa: F401
