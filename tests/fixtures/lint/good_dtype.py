"""Clean twins of bad_dtype.py: derived or explicitly-audited dtypes."""

import jax.numpy as jnp
import numpy as np

from raft_tpu.utils.dtypes import compute_dtypes


def traced_allocations(x, nw):
    rdt, cdt = compute_dtypes(x)
    a = jnp.zeros(nw, dtype=cdt)
    b = jnp.ones((3, nw), dtype=cdt)
    c = jnp.full(nw, 1.0, dtype=rdt)
    return a, b, c, a.astype(cdt)


def host_allocation(nw):
    # explicit 64-bit width: audited host-side precision, not a leak
    return np.zeros(nw, dtype=np.complex128)
