"""Live fleet-health fast units: alert-rule parsing/predicates/
for-duration/resolve hysteresis, rule-file loading, the default pack,
record replay (``alerts eval --record``), golden-canary capture +
tolerance comparison, provenance codec round-trip and cross-replica
consistency, and the obs-report alerts/provenance sections.

Everything here is socket-free and compile-free (tier-1): the engine
runs on an injected clock, the canary core is fed synthetic rows, and
the CLI verbs are called in-process.
"""

import json
import os

import numpy as np
import pytest

from tests._obs_helpers import read_events

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "fixtures", "runs")


# ---------------------------------------------------------- rule parsing


def test_parse_rule_good_and_bad():
    from raft_tpu.obs.alerts import parse_rule

    r = parse_rule({"name": "x", "metric": "counter:serve_errors",
                    "predicate": "rate_above", "threshold": 2,
                    "for_s": 1, "clear_s": 3, "severity": "critical",
                    "context": "canary_parity", "help": "h"})
    assert r.name == "x" and r.threshold == 2.0 and r.clear_s == 3.0
    assert r.context == "canary_parity"
    with pytest.raises(ValueError, match="name"):
        parse_rule({"metric": "counter:x", "predicate": "above"})
    with pytest.raises(ValueError, match="selector"):
        parse_rule({"name": "x", "metric": "serve_errors",
                    "predicate": "above"})
    with pytest.raises(ValueError, match="predicate"):
        parse_rule({"name": "x", "metric": "counter:x",
                    "predicate": "gte"})
    with pytest.raises(ValueError, match="severity"):
        parse_rule({"name": "x", "metric": "counter:x",
                    "predicate": "above", "severity": "page"})
    with pytest.raises(ValueError, match="for_s"):
        parse_rule({"name": "x", "metric": "counter:x",
                    "predicate": "above", "for_s": -1})
    with pytest.raises(ValueError, match="unknown field"):
        parse_rule({"name": "x", "metric": "counter:x",
                    "predicate": "above", "threshhold": 3})


def test_load_rules_json_yaml_override_disable(tmp_path):
    from raft_tpu.obs.alerts import default_rules, load_rules

    names = {r.name for r in default_rules()}
    assert {"slo-breach", "breaker-storm", "lease-churn",
            "cache-hit-collapse", "compile-budget-burn",
            "canary-failure", "canary-parity"} == names
    # default pack when no file
    assert {r.name for r in load_rules(None)} == names
    # JSON: override one (same name replaces), add one, disable one
    jf = tmp_path / "rules.json"
    jf.write_text(json.dumps({"rules": [
        {"name": "slo-breach", "metric": "counter:serve_slo_breaches",
         "predicate": "rate_above", "threshold": 9.0},
        {"name": "my-rule", "metric": "hist:serve_request_s:p95",
         "predicate": "above", "threshold": 2.0},
        {"name": "lease-churn", "disabled": True},
    ]}))
    rules = {r.name: r for r in load_rules(str(jf))}
    assert rules["slo-breach"].threshold == 9.0
    assert "my-rule" in rules and "lease-churn" not in rules
    # YAML: default_pack false starts empty
    yf = tmp_path / "rules.yaml"
    yf.write_text("default_pack: false\n"
                  "rules:\n"
                  "  - name: only\n"
                  "    metric: gauge:router_replicas:value\n"
                  "    predicate: below\n"
                  "    threshold: 2\n")
    only = load_rules(str(yf))
    assert [r.name for r in only] == ["only"]
    # a bad file is a loud ValueError (the `alerts check` exit-1 path)
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"rules": [{"name": "x"}]}))
    with pytest.raises(ValueError):
        load_rules(str(bad))


def test_rate_rule_fires_on_mid_life_minted_counter():
    """Counters are created on their FIRST increment (breaker opens,
    evictions, canary failures) — a counter appearing after the
    engine's first pass must register as a rate from 0, not silently
    become the baseline; totals pre-existing the engine (warmup
    compiles) must baseline without firing."""
    from raft_tpu.obs.alerts import Rule

    eng, _ = _engine([
        Rule("storm", "counter:router_breaker_opens", "rate_above",
             threshold=0.0, clear_s=0.0),
        Rule("burn", "counter:xla_real_compiles", "rate_above",
             threshold=0.0, clear_s=0.0)])
    # first pass: warmup compiles already at 5 — baseline, NO fire
    assert eng.evaluate({"counter:xla_real_compiles": 5.0}, now=0.0) == []
    # breaker counter minted mid-life (SIGKILL just landed): fires on
    # the very next pass — "within one eval interval"
    t = eng.evaluate({"counter:xla_real_compiles": 5.0,
                      "counter:router_breaker_opens": 1.0}, now=1.0)
    assert [(x["rule"], x["kind"]) for x in t] == [("storm", "fire")]
    # storm over: opens flat -> resolve
    t = eng.evaluate({"counter:xla_real_compiles": 5.0,
                      "counter:router_breaker_opens": 1.0}, now=2.0)
    assert [(x["rule"], x["kind"]) for x in t] == [("storm", "resolve")]


# ------------------------------------------------------------ predicates


def _engine(rules, sink=None):
    from raft_tpu.obs.alerts import AlertEngine

    clock = [0.0]
    eng = AlertEngine(rules, sink_path=sink, clock=lambda: clock[0])
    return eng, clock


def test_predicates_above_below_rate_absent():
    from raft_tpu.obs.alerts import Rule

    eng, _clock = _engine([
        Rule("a", "gauge:g:value", "above", threshold=5.0),
        Rule("b", "gauge:g:value", "below", threshold=1.0),
        Rule("r", "counter:c", "rate_above", threshold=2.0),
        Rule("m", "counter:gone", "absent"),
    ])
    # t=0: establishes the rate baseline; gauge mid-range; counter
    # present -> only the absence rule can fire (metric 'gone' missing)
    t1 = eng.evaluate({"gauge:g:value": 3.0, "counter:c": 0.0}, now=0.0)
    assert [t["rule"] for t in t1] == ["m"]
    # t=10: counter +30 in 10s = 3/s > 2 -> rate fires; gauge 6 > 5
    t2 = eng.evaluate({"gauge:g:value": 6.0, "counter:c": 30.0,
                       "counter:gone": 1.0}, now=10.0)
    assert sorted(t["rule"] for t in t2 if t["kind"] == "fire") \
        == ["a", "r"]
    assert [t["rule"] for t in t2 if t["kind"] == "resolve"] == ["m"]
    # t=20: counter flat -> rate 0 -> resolve; gauge 0.5 < 1 -> below
    t3 = eng.evaluate({"gauge:g:value": 0.5, "counter:c": 30.0},
                      now=20.0)
    kinds = {(t["rule"], t["kind"]) for t in t3}
    assert ("b", "fire") in kinds and ("r", "resolve") in kinds
    assert ("a", "resolve") in kinds
    # counter RESET (process restart): a drop must re-baseline, never
    # fire as a negative-or-huge rate
    t4 = eng.evaluate({"gauge:g:value": 0.5, "counter:c": 1.0}, now=30.0)
    assert not any(t["rule"] == "r" for t in t4)


def test_for_duration_and_resolve_hysteresis():
    from raft_tpu.obs.alerts import Rule

    eng, _ = _engine([Rule("slow", "gauge:g:value", "above",
                           threshold=1.0, for_s=10.0, clear_s=5.0)])
    # condition true but younger than for_s: pending, no fire
    assert eng.evaluate({"gauge:g:value": 2.0}, now=0.0) == []
    assert eng.evaluate({"gauge:g:value": 2.0}, now=9.0) == []
    t = eng.evaluate({"gauge:g:value": 2.0}, now=10.0)
    assert [x["kind"] for x in t] == ["fire"]
    assert eng.active() and eng.active()[0]["rule"] == "slow"
    # a blip below the threshold RESETS the pending clock next time,
    # but a firing alert needs clear_s of clean before resolving
    assert eng.evaluate({"gauge:g:value": 0.0}, now=12.0) == []  # clean 0s
    # condition returns inside the clear window: still firing, no
    # re-fire event (hysteresis absorbs the flap)
    assert eng.evaluate({"gauge:g:value": 2.0}, now=14.0) == []
    assert eng.evaluate({"gauge:g:value": 0.0}, now=20.0) == []
    t = eng.evaluate({"gauge:g:value": 0.0}, now=25.0)
    assert [x["kind"] for x in t] == ["resolve"]
    assert t[0]["duration_s"] == pytest.approx(15.0)
    assert eng.active() == []
    # pending was reset by the earlier dip: a fresh fire needs a fresh
    # uninterrupted for_s window
    assert eng.evaluate({"gauge:g:value": 2.0}, now=26.0) == []
    assert [x["kind"] for x in
            eng.evaluate({"gauge:g:value": 2.0}, now=36.0)] == ["fire"]


def test_fire_emits_events_sink_gauge_and_context(tmp_path, monkeypatch):
    from raft_tpu.obs import alerts, metrics
    from raft_tpu.obs.alerts import Rule, read_sink

    metrics.reset()
    log = tmp_path / "events.jsonl"
    sink = tmp_path / "alerts.jsonl"
    monkeypatch.setenv("RAFT_TPU_LOG", str(log))
    eng, _ = _engine([Rule("boom", "counter:c", "above", threshold=0.0,
                           severity="critical", context="canary_parity")],
                     sink=str(sink))
    alerts.set_context("canary_parity", {"offending": "rB"})
    try:
        eng.evaluate({"counter:c": 3.0}, now=1.0)
        assert metrics.gauge("alerts_active").value == 1.0
        assert metrics.counter("alerts_fired").value == 1
        eng.evaluate({"counter:c": 0.0}, now=2.0)
        assert metrics.gauge("alerts_active").value == 0.0
        assert metrics.counter("alerts_resolved").value == 1
    finally:
        alerts.set_context("canary_parity", None)
    fires = read_events(log, name="alert_fire")
    resolves = read_events(log, name="alert_resolve")
    assert len(fires) == 1 and len(resolves) == 1
    assert fires[0]["rule"] == "boom" and fires[0]["severity"] == "critical"
    assert fires[0]["context"] == {"offending": "rB"}
    assert resolves[0]["duration_s"] == pytest.approx(1.0)
    # the JSONL sink holds the same two transition records
    records, bad = read_sink(str(sink))
    assert bad == 0 and [r["kind"] for r in records] == ["fire", "resolve"]
    assert records[0]["rule"] == "boom"
    assert records[0]["context"] == {"offending": "rB"}
    assert records[1]["duration_s"] == pytest.approx(1.0)
    from raft_tpu.obs.alerts import render_sink_summary

    lines = render_sink_summary(records)
    assert len(lines) == 2 and "boom" in lines[0]


def test_flatten_snapshot_gauge_value_and_derived(monkeypatch):
    from raft_tpu.obs import metrics
    from raft_tpu.obs.alerts import flatten_snapshot

    metrics.reset()
    metrics.counter("serve_cache_hits").inc(3)
    metrics.counter("serve_cache_misses").inc(1)
    metrics.gauge("canary_parity_ok").set(0.0)
    metrics.histogram("serve_request_s").observe(0.1)
    flat = flatten_snapshot(metrics.snapshot())
    assert flat["derived:serve_cache_hit_rate"] == pytest.approx(0.75)
    assert flat["gauge:canary_parity_ok:value"] == 0.0
    assert flat["counter:serve_cache_hits"] == 3.0
    assert "hist:serve_request_s:p95" in flat
    metrics.reset()


def test_maybe_start_zero_overhead(monkeypatch):
    from raft_tpu.obs import alerts

    monkeypatch.delenv("RAFT_TPU_ALERT_EVAL_S", raising=False)
    assert alerts.maybe_start() is None
    assert alerts.installed_engine() is None
    payload = alerts.endpoint_payload()
    assert payload["enabled"] is False and payload["active"] == []
    alerts.stop()  # idempotent no-op


def test_maybe_start_and_stop_lifecycle(monkeypatch, tmp_path):
    from raft_tpu.obs import alerts

    monkeypatch.setenv("RAFT_TPU_ALERT_EVAL_S", "30")
    try:
        daemon = alerts.maybe_start()
        assert daemon is not None and daemon.is_alive()
        assert daemon.daemon and daemon.name == "raft-alert-eval"
        assert alerts.maybe_start() is daemon  # idempotent
        payload = alerts.endpoint_payload()
        assert payload["enabled"] and len(payload["rules"]) == 7
    finally:
        alerts.stop()
    assert alerts.installed_engine() is None
    assert not daemon.is_alive()


# ---------------------------------------------------------------- replay


def test_alerts_eval_cli_clean_and_seeded(capsys):
    from raft_tpu.obs.__main__ import main

    assert main(["alerts", "check"]) == 0
    assert main(["alerts", "eval", "--record",
                 os.path.join(FIXTURES, "clean.json")]) == 0
    rc = main(["alerts", "eval", "--record",
               os.path.join(FIXTURES, "alerting.json")])
    assert rc == 1
    out = capsys.readouterr().out
    assert "breaker-storm" in out and "canary-parity" in out
    assert "slo-breach" in out


def test_alerts_check_cli_rejects_bad_file(tmp_path, capsys):
    from raft_tpu.obs.__main__ import main

    bad = tmp_path / "r.json"
    bad.write_text(json.dumps({"rules": [
        {"name": "x", "metric": "nope", "predicate": "above"}]}))
    assert main(["alerts", "check", "--rules", str(bad)]) == 1
    assert main(["alerts", "list"]) == 0
    assert "breaker-storm" in capsys.readouterr().out


def test_compile_budget_burn_sees_sentinel_counts():
    """The recompile sentinel's counts live OUTSIDE the metrics
    snapshot (record['compiles'], /healthz) — flatten must fold them
    into the counter: namespace or the compile-budget-burn rule can
    never fire, live or in replay."""
    from raft_tpu.obs.alerts import (default_rules, flatten_record,
                                     replay_rules)

    record = {"snapshot": {}, "compiles": {"xla_compiles": 12,
                                           "xla_real_compiles": 3}}
    flat = flatten_record(record)
    assert flat["counter:xla_real_compiles"] == 3.0
    assert flat["counter:xla_compiles"] == 12.0
    fired, _checked = replay_rules(default_rules(), record)
    assert [f["rule"] for f in fired] == ["compile-budget-burn"]
    # a genuinely-in-snapshot counter of the same name wins (setdefault)
    flat2 = flatten_record({"snapshot": {"counters":
                                         {"xla_real_compiles": 7}},
                            "compiles": {"xla_real_compiles": 3}})
    assert flat2["counter:xla_real_compiles"] == 7.0


def test_replay_rate_rules_use_replay_threshold():
    from raft_tpu.obs.alerts import Rule, replay_rules

    record = {"snapshot": {"counters": {"shard_retries": 2}}}
    # cumulative 2 > replay_above 0 -> fires; raising replay_above
    # above the total silences it; absent metric does not apply
    fired, checked = replay_rules(
        [Rule("r", "counter:shard_retries", "rate_above", threshold=5.0),
         Rule("quiet", "counter:shard_retries", "rate_above",
              threshold=5.0, replay_above=10.0),
         Rule("gone", "counter:never_minted", "rate_above")], record)
    assert checked == 2
    assert [f["rule"] for f in fired] == ["r"]


# ---------------------------------------------------------------- canary


def _mk_canary(rtol=1e-5, atol=1e-8):
    from raft_tpu.serve.canary import CanaryState

    return CanaryState(rtol=rtol, atol=atol)


def _row(x0=(1.0, 2.0, 3.0), status=0):
    return {"X0": np.asarray(x0, dtype=float),
            "status": np.int32(status)}


def test_canary_golden_capture_and_tolerance_compare(monkeypatch):
    from raft_tpu.obs import metrics

    metrics.reset()
    c = _mk_canary(rtol=1e-6, atol=1e-9)
    keys = ("X0", "status")
    row = _row()
    prov = {"bank_sha": "aa", "code": "cc", "flags": "ff", "replica": "rA"}
    v = c.observe("spar", "rA", "fp-spar", (4.0, 9.0, 0.0), keys,
                  row, row["status"], provenance=prov)
    assert v["ok"] and v["golden_created"] and v["reason"] == "golden"
    # bit-identical repeat from another replica (same provenance modulo
    # replica id): pass
    v = c.observe("spar", "rB", "fp-spar", (4.0, 9.0, 0.0), keys,
                  _row(), np.int32(0),
                  provenance={**prov, "replica": "rB"})
    assert v["ok"] and not v["golden_created"]
    # inside tolerance: pass; outside: fail with a named delta
    v = c.observe("spar", "rB", "fp-spar", (4.0, 9.0, 0.0), keys,
                  _row((1.0 + 1e-9, 2.0, 3.0)), 0,
                  provenance={**prov, "replica": "rB"})
    assert v["ok"]
    v = c.observe("spar", "rB", "fp-spar", (4.0, 9.0, 0.0), keys,
                  _row((1.1, 2.0, 3.0)), 0,
                  provenance={**prov, "replica": "rB"})
    assert not v["ok"] and "delta" in v["reason"]
    assert metrics.gauge("canary_parity_ok").value == 0.0
    assert metrics.counter("canary_fail").value == 1
    # a clean follow-up clears the failing key and parity recovers
    v = c.observe("spar", "rB", "fp-spar", (4.0, 9.0, 0.0), keys,
                  _row(), 0, provenance={**prov, "replica": "rB"})
    assert v["ok"] and metrics.gauge("canary_parity_ok").value == 1.0
    metrics.reset()


def test_canary_status_is_bit_exact():
    c = _mk_canary(rtol=1.0, atol=1.0)  # floats effectively ignored
    keys = ("X0", "status")
    c.observe("spar", "rA", "fp", (4.0, 9.0, 0.0), keys, _row(), 4)
    v = c.observe("spar", "rB", "fp", (4.0, 9.0, 0.0), keys, _row(), 6)
    assert not v["ok"] and "bit-exact" in v["reason"]
    # same bits pass even when SEVERE: determinism, not health, is the
    # canary's contract
    v = c.observe("spar", "rB", "fp", (4.0, 9.0, 0.0), keys, _row(), 4)
    assert v["ok"]


def test_canary_provenance_split_sets_context(monkeypatch):
    from raft_tpu.obs import alerts, metrics
    from raft_tpu.obs.alerts import Rule

    metrics.reset()
    c = _mk_canary()
    keys = ("X0", "status")
    good = {"bank_key": "k1", "bank_sha": "aaaa", "code": "c1",
            "flags": "f1", "replica": "rA"}
    skew = {"bank_key": "skew-k1", "bank_sha": "skewaaaa", "code": "c1",
            "flags": "f1", "replica": "rB"}
    c.observe("spar", "rA", "fp", (4.0, 9.0, 0.0), keys, _row(), 0,
              provenance=good)
    v = c.observe("spar", "rB", "fp", (4.0, 9.0, 0.0), keys, _row(), 0,
                  provenance=skew)
    # numerically identical, yet the provenance split alarms — the
    # stale-bank/env-skew class health bits cannot see
    assert not v["ok"] and v["provenance_ok"] is False
    assert metrics.gauge("canary_parity_ok").value == 0.0
    ctx = alerts.get_context("canary_parity")
    assert ctx is not None
    splits = ctx["provenance"]["splits"]
    fields = {s["field"] for s in splits}
    assert {"bank_sha", "bank_key"} <= fields
    by_field = {s["field"]: s for s in splits}
    assert by_field["bank_sha"]["values"]["rB"] == "skewaaaa"
    # the canary-parity rule fires on the gauge and carries the context
    from raft_tpu.obs.alerts import AlertEngine, flatten_snapshot

    eng = AlertEngine([r for r in alerts.default_rules()
                       if r.name == "canary-parity"],
                      clock=lambda: 100.0)
    t = eng.evaluate(flatten_snapshot(metrics.snapshot()))
    assert [x["kind"] for x in t] == ["fire"]
    assert t[0]["context"]["provenance"]["splits"]
    summary = c.summary()
    assert summary["parity_ok"] is False
    assert not summary["provenance"]["consistent"]
    alerts.set_context("canary_parity", None)
    metrics.reset()


def test_canary_prune_clears_departed_replica_ghost(monkeypatch):
    """A replaced replica's provenance stamp must not ghost-split
    parity forever: pruning to the current membership recovers the
    gauge and clears the alert context (the rolling-upgrade story)."""
    from raft_tpu.obs import alerts, metrics

    metrics.reset()
    c = _mk_canary()
    keys = ("X0", "status")
    old = {"bank_key": "k", "bank_sha": "aaaa", "code": "OLD",
           "flags": "f", "replica": "rA"}
    new = {"bank_key": "k", "bank_sha": "aaaa", "code": "NEW",
           "flags": "f", "replica": "rC"}
    c.observe("spar", "rA", "fp", (4.0, 9.0, 0.0), keys, _row(), 0,
              provenance=old)
    v = c.observe("spar", "rC", "fp", (4.0, 9.0, 0.0), keys, _row(), 0,
                  provenance=new)
    assert not v["provenance_ok"]            # genuine split while both live
    assert metrics.gauge("canary_parity_ok").value == 0.0
    # rA drains and leaves the fleet: prune to the surviving membership
    assert c.prune({"rC"}) is True
    assert metrics.gauge("canary_parity_ok").value == 1.0
    assert alerts.get_context("canary_parity") is None
    assert c.summary()["parity_ok"] is True
    assert c.prune({"rC"}) is False          # idempotent no-op
    metrics.reset()


def test_read_sink_requires_kind(tmp_path):
    from raft_tpu.obs.alerts import read_sink, render_sink_summary

    sink = tmp_path / "s.jsonl"
    sink.write_text(json.dumps({"rule": "x"}) + "\n"
                    + json.dumps({"kind": "fire", "rule": "y",
                                  "severity": "info", "metric": "m",
                                  "value": 1}) + "\n")
    records, bad = read_sink(str(sink))
    assert bad == 1 and [r["rule"] for r in records] == ["y"]
    assert len(render_sink_summary(records)) == 1  # no KeyError


def test_router_canary_probe_intersects_lease_out_keys(monkeypatch):
    """A replica whose lease declares a narrower served out_keys set
    is probed with the intersection (status-only at minimum) — a probe
    asking for an unserved key would 400 and the canary would be
    silently inert."""
    from raft_tpu.obs import metrics
    from raft_tpu.serve.canary import RouterCanary
    from raft_tpu.serve.router import RouterState

    metrics.reset()
    monkeypatch.setenv("RAFT_TPU_CANARY_S", "30")
    monkeypatch.delenv("RAFT_TPU_CANARY_OUT_KEYS", raising=False)
    state = RouterState(vnodes=8)
    state.apply_membership({
        "narrow": {"addr": "h", "port": 1, "out_keys": ["PSD", "status"],
                   "designs": {"spar": {"sig": "s", "fingerprint": "fp"}}},
        "legacy": {"addr": "h", "port": 2,   # pre-out_keys lease
                   "designs": {"spar": {"sig": "s", "fingerprint": "fp"}}},
    })
    assert state.served_out_keys("narrow") == ("PSD", "status")
    assert state.served_out_keys("legacy") == ()
    asked = {}

    def probe(addr, port, design, case, out_keys):
        asked[port] = out_keys
        return 200, {"ok": True, "status": 0,
                     "outputs": {"status": 0}}, None

    rc = RouterCanary(state, probe=probe)
    rc.probe_once()
    # narrow lease: X0 is unserved -> probe asks status only; the
    # legacy lease declares nothing -> configured default
    assert asked[1] == ("status",)
    assert asked[2] == ("X0", "status")
    metrics.reset()


def test_decode_outputs_complex_round_trip():
    from raft_tpu.serve.canary import decode_outputs
    from raft_tpu.serve.http import _json_value

    z = np.asarray([1.0 + 2.0j, -0.5 - 1.0j])
    x = np.asarray([1.5, 2.5])
    decoded = decode_outputs({"Z": _json_value(z), "X": _json_value(x)})
    np.testing.assert_array_equal(decoded["Z"], z)
    np.testing.assert_array_equal(decoded["X"], x)


def test_canary_out_keys_served_intersection(monkeypatch):
    from raft_tpu.serve.canary import canary_out_keys

    monkeypatch.delenv("RAFT_TPU_CANARY_OUT_KEYS", raising=False)
    assert canary_out_keys() == ("X0", "status")
    assert canary_out_keys(served=("PSD", "status")) == ("status",)
    monkeypatch.setenv("RAFT_TPU_CANARY_OUT_KEYS", "PSD,X0")
    assert canary_out_keys(served=("PSD", "X0", "status")) \
        == ("PSD", "X0", "status")


def test_router_canary_probe_once_with_injected_probe(monkeypatch):
    """Socket-free router-canary pass: injected probe fn, RouterState
    membership — verdicts flow per (replica, design) and a skewed
    replica is named."""
    from raft_tpu.obs import alerts, metrics
    from raft_tpu.serve.canary import RouterCanary
    from raft_tpu.serve.router import RouterState

    metrics.reset()
    monkeypatch.setenv("RAFT_TPU_CANARY_S", "30")
    state = RouterState(vnodes=8)
    state.apply_membership({
        "rA": {"addr": "127.0.0.1", "port": 1,
               "designs": {"spar": {"sig": "s", "fingerprint": "fp"}}},
        "rB": {"addr": "127.0.0.1", "port": 2,
               "designs": {"spar": {"sig": "s", "fingerprint": "fp"}}},
    })
    provs = {1: {"bank_sha": "aaaa", "bank_key": "k", "code": "c",
                 "flags": "f", "replica": "rA"},
             2: {"bank_sha": "bbbb", "bank_key": "skew-k", "code": "c",
                 "flags": "f", "replica": "rB"}}

    def probe(addr, port, design, case, out_keys):
        body = {"ok": True, "status": 0, "cache_hit": False,
                "outputs": {"X0": [1.0, 2.0], "status": 0}}
        return 200, body, provs[port]

    rc = RouterCanary(state, probe=probe)
    assert rc.daemon and rc.name == "raft-router-canary"
    verdicts = rc.probe_once()
    assert len(verdicts) == 2
    assert verdicts[0]["ok"]              # first probe mints the golden
    assert not verdicts[1]["provenance_ok"]
    assert metrics.counter("canary_fail").value == 1
    summary = rc.canary.summary()
    assert summary["goldens"] == 1 and not summary["parity_ok"]
    split_values = summary["provenance"]["splits"][0]["values"]
    assert set(split_values) == {"rA", "rB"}
    alerts.set_context("canary_parity", None)
    metrics.reset()


# ------------------------------------------------------ provenance codec


def test_provenance_format_parse_round_trip():
    from raft_tpu.obs.alerts import format_provenance, parse_provenance

    prov = {"bank_key": "abc123", "bank_sha": "deadbeef",
            "code": "c0ffee", "flags": "f00", "replica": "rA-1"}
    s = format_provenance(prov)
    assert s == ("bank_key=abc123;bank_sha=deadbeef;code=c0ffee;"
                 "flags=f00;replica=rA-1")
    assert parse_provenance(s) == prov
    # header-hostile characters are sanitized, never smuggled
    s2 = format_provenance({"bank_key": "a;b=c d", "replica": "r"})
    assert ";b" not in s2.split(";", 1)[1] if ";" in s2 else True
    assert parse_provenance(s2)["bank_key"] == "a_b_c_d"
    # garbled/empty values parse to None, never crash
    assert parse_provenance(None) is None
    assert parse_provenance("") is None
    assert parse_provenance("no-equals-signs") is None


def test_provenance_consistency_verdicts():
    from raft_tpu.obs.alerts import provenance_consistency

    a = {"bank_sha": "x", "bank_key": "k", "code": "c", "flags": "f",
         "replica": "rA"}
    b = {**a, "replica": "rB"}
    ok = provenance_consistency({"spar": {"rA": a, "rB": b}})
    assert ok["consistent"] and ok["splits"] == []
    # replica id differing is NOT a split; bank_sha differing is
    bad = provenance_consistency(
        {"spar": {"rA": a, "rB": {**b, "bank_sha": "y"}}})
    assert not bad["consistent"]
    assert bad["splits"][0]["field"] == "bank_sha"
    assert bad["splits"][0]["values"] == {"rA": "x", "rB": "y"}
    # one replica per design: nothing to compare
    assert provenance_consistency({"spar": {"rA": a}})["consistent"]


# ------------------------------------------------------- report sections


def _anchor():
    return {"t": 0.0, "event": "proc_start", "unix_t": 0.0,
            "argv0": "x", "pid": 1}


def test_report_alerts_and_canary_section():
    from raft_tpu.obs.report import render_report, report_data

    events = [_anchor()]
    events.append({"t": 1.0, "pid": 1, "event": "alert_fire",
                   "rule": "breaker-storm", "severity": "critical",
                   "metric": "counter:router_breaker_opens",
                   "value": 1.0, "threshold": 0.0, "context": None})
    events.append({"t": 2.0, "pid": 1, "event": "alert_resolve",
                   "rule": "breaker-storm", "severity": "critical",
                   "metric": "counter:router_breaker_opens",
                   "duration_s": 1.0, "value": 0.0})
    events.append({"t": 3.0, "pid": 1, "event": "alert_fire",
                   "rule": "canary-parity", "severity": "critical",
                   "metric": "gauge:canary_parity_ok:value",
                   "value": 0.0, "threshold": 1.0,
                   "context": {"failing": {}}})
    events.append({"t": 0.5, "pid": 1, "event": "canary_golden",
                   "design": "spar", "key": "k", "status": 0,
                   "replica": "rA"})
    for i, ok in enumerate((True, True, False)):
        events.append({"t": 1.0 + i, "pid": 1, "event": "canary_check",
                       "design": "spar", "replica": "rB", "ok": ok,
                       "reason": "match" if ok else "status 4 != 0",
                       "provenance_ok": ok, "key": "k"})
    data = report_data(events)
    a = data["alerts"]
    assert a["rules"]["breaker-storm"] == {"severity": "critical",
                                           "fires": 1, "resolves": 1}
    assert a["active_at_end"] == ["canary-parity"]
    assert a["canary"] == {"goldens": 1, "checks": 3, "failed": 1,
                           "provenance_failures": 1}
    text = render_report(events)
    assert "alerts & canaries" in text
    assert "STILL FIRING at capture end: canary-parity" in text
    assert "1 failed (1 provenance split(s))" in text
    # no alert/canary events -> no section
    assert report_data([_anchor()])["alerts"] is None


def test_report_router_provenance_consistency_line():
    from raft_tpu.obs.alerts import format_provenance
    from raft_tpu.obs.report import render_report, report_data

    good = format_provenance({"bank_key": "k", "bank_sha": "aaaa",
                              "code": "c", "flags": "f", "replica": "rA"})
    skew = format_provenance({"bank_key": "k", "bank_sha": "bbbb",
                              "code": "c", "flags": "f", "replica": "rB"})
    events = [_anchor()]
    for i, (rid, prov) in enumerate((("rA", good), ("rB", good))):
        events.append({"t": 0.1 * i, "pid": 1, "event": "router_request",
                       "replica": rid, "code": 200, "attempts": 1,
                       "hedged": False, "design": "spar",
                       "wall_s": 0.01, "provenance": prov})
    data = report_data(events)
    prov = data["router"]["provenance"]
    assert prov["consistent"] and prov["replicas"] == ["rA", "rB"]
    assert "provenance: consistent" in render_report(events)
    # divergent bank sha on rB: the section names the split
    events[-1]["provenance"] = skew
    data = report_data(events)
    prov = data["router"]["provenance"]
    assert not prov["consistent"]
    assert prov["splits"][0]["values"]["rB"] == "bbbb"
    text = render_report(events)
    assert "INCONSISTENT" in text and "rB=bbbb" in text
