"""CLI for the trace-hygiene + concurrency-invariant suite.

    python -m raft_tpu.analysis lint [paths...]
    python -m raft_tpu.analysis concurrency [paths...]
    python -m raft_tpu.analysis schemas [--write | --fixture]
    python -m raft_tpu.analysis contracts [--design YAML] [--modes ...]
    python -m raft_tpu.analysis baseline --write [--design YAML]
    python -m raft_tpu.analysis flags

Exit codes: 0 clean, 1 findings/violations, 2 usage error.  ``lint``,
``concurrency``, ``schemas`` and ``flags`` are jax-free;
``contracts``/``baseline`` trace the entry points and pin the CPU
backend first (accelerator plugins in this image can hang backend init
— the lint gate must never).
"""

from __future__ import annotations

import argparse
import sys


def _cmd_lint(args):
    from raft_tpu.analysis import lint

    findings = lint.lint_paths(args.paths or None)
    if not args.paths:
        # the dead-entry audit only makes sense over the full scan set
        # (a partial path list would flag every registration as dead)
        findings.extend(lint.registered_unused())
    for f in findings:
        print(f.format())
    if findings:
        print(f"{len(findings)} finding(s). Suppress intentional ones with "
              "`# raft-lint: disable=<rule>`.", file=sys.stderr)
        return 1
    print("lint clean "
          f"({len(args.paths) or len(lint.default_paths())} files).")
    return 0


def _cmd_concurrency(args):
    from raft_tpu.analysis import concurrency

    findings = concurrency.analyze_paths(args.paths or None)
    for f in findings:
        print(f.format())
    if findings:
        print(f"{len(findings)} finding(s). Suppress audited exceptions "
              "with `# raft-lint: disable=<rule>`.", file=sys.stderr)
        return 1
    scope = (f"{len(args.paths)} file(s)" if args.paths
             else "shared-state + serve modules")
    print(f"concurrency invariants clean ({scope}).")
    return 0


def _cmd_schemas(args):
    from raft_tpu.analysis import schemas

    if args.fixture:
        violations, _ = schemas.run_fixture_checks()
        for v in violations:
            print(v)
        if not violations:
            print("schema drift fixture produced NO violations — the "
                  "engine is broken", file=sys.stderr)
            return 2
        print(f"{len(violations)} violation(s) (seeded fixture drill).",
              file=sys.stderr)
        return 1
    if args.write:
        contracts = schemas.extract_all()
        drift = []
        for name, contract in contracts.items():
            drift.extend(schemas.drift_violations(name, contract))
        if drift:
            # never bake live writer/reader drift into the baseline
            for v in drift:
                print(v, file=sys.stderr)
            print("refusing to write a baseline over live drift",
                  file=sys.stderr)
            return 1
        path = schemas.write_baseline(contracts)
        print(f"schema baseline written: {path} "
              f"({len(contracts)} families)")
        return 0
    violations, contracts = schemas.run_checks()
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} schema-contract violation(s). "
              "Intentional evolution: `python -m raft_tpu.analysis "
              "schemas --write` and commit the diff.", file=sys.stderr)
        return 1
    n_keys = sum(len(c["written"]) + len(c["read"])
                 for c in contracts.values())
    print(f"schema contracts clean ({len(contracts)} families, "
          f"{n_keys} keys).")
    return 0


def _pin_cpu():
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)


def _cmd_contracts(args, update_baseline=False):
    _pin_cpu()
    from raft_tpu.analysis import jaxpr_contracts as jc

    modes = [m.strip() for m in args.modes.split(",") if m.strip()]
    report = jc.run_checks(design=args.design, dtype_modes=modes,
                           update_baseline=update_baseline)
    for line in report["log"]:
        print(line)
    if report["violations"]:
        print(f"{len(report['violations'])} contract violation(s):",
              file=sys.stderr)
        for v in report["violations"]:
            print("  " + v, file=sys.stderr)
        return 1
    if update_baseline:
        print(f"baseline written: {jc.baseline_path()}")
    print("jaxpr contracts clean.")
    return 0


def _cmd_baseline(args):
    if not args.write:
        print("baseline is checked in; pass --write to regenerate "
              "(after an intentional hot-path change)", file=sys.stderr)
        return 2
    return _cmd_contracts(args, update_baseline=True)


def _cmd_flags(_args):
    from raft_tpu.utils import config

    rows = list(config.describe())
    w = max(len(r[0]) for r in rows)
    for env, kind, default, help_ in rows:
        print(f"{env:<{w}}  {kind:<6}  default={default!r}  {help_}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m raft_tpu.analysis")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("lint", help="run the trace-hygiene AST linter")
    p.add_argument("paths", nargs="*", help="files to lint "
                   "(default: raft_tpu/ + bench.py + sweep_10k.py)")

    p = sub.add_parser(
        "concurrency",
        help="concurrency invariants: atomic-write, async-blocking, "
             "lock-discipline, thread-hygiene")
    p.add_argument("paths", nargs="*",
                   help="files to analyze with every rule forced on "
                        "(default: the audited shared-state + serve "
                        "modules with per-module rule gating)")

    p = sub.add_parser(
        "schemas",
        help="cross-process writer/reader schema contracts vs the "
             "checked-in analysis/schema_baseline.json")
    p.add_argument("--write", action="store_true",
                   help="regenerate the baseline (intentional schema "
                        "evolution; refuses over live drift)")
    p.add_argument("--fixture", action="store_true",
                   help="run the seeded drifted-lease fixture drill "
                        "(must exit 1 — the CI negative)")

    for name in ("contracts", "baseline"):
        p = sub.add_parser(
            name, help=("check jaxpr contracts + primitive budgets"
                        if name == "contracts"
                        else "regenerate the primitive-count baseline"))
        p.add_argument("--design", default=None,
                       help="design YAML (default: bundled spar_demo)")
        p.add_argument("--modes", default="float64,float32",
                       help="comma list of RAFT_TPU_DTYPE modes to trace")
        if name == "baseline":
            p.add_argument("--write", action="store_true")

    sub.add_parser("flags", help="list the RAFT_TPU_* flag registry")

    args = ap.parse_args(argv)
    cmd = {"lint": _cmd_lint, "concurrency": _cmd_concurrency,
           "schemas": _cmd_schemas, "contracts": _cmd_contracts,
           "baseline": _cmd_baseline, "flags": _cmd_flags}[args.cmd]
    return cmd(args)


if __name__ == "__main__":
    sys.exit(main())
