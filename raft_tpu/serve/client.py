"""Minimal stdlib client for the evaluation service / fleet router.

Used by the bench load harness (``RAFT_TPU_BENCH_MODE=serve``) and the
subprocess tests; keep-alive ``http.client`` connections so hundreds of
synthetic clients stay cheap.  Not a public SDK — the wire format is
plain JSON over HTTP (see :mod:`raft_tpu.serve.http`).

Backpressure-aware retries: with ``retries=`` (default from
``RAFT_TPU_SERVE_CLIENT_RETRIES``, 0 = off) a 429/503 response is
retried after a capped exponential backoff that honors the server's
``Retry-After`` — :func:`backoff_delay` is the ONE schedule shared by
this client and the fleet router's failover ladder
(:mod:`raft_tpu.serve.router`), so the bench load generator and the
router back off identically.  Only CLEAN backpressure responses are
retried; a dropped response stays :class:`ResponseDropped` (re-sending
a possibly-accepted evaluate is the caller's call, never the
client's).
"""

from __future__ import annotations

import http.client
import json
import random
import time

from raft_tpu.utils import config

#: responses the client-side retry loop may re-send: both are CLEAN
#: rejections (the request was never evaluated), so a re-send cannot
#: duplicate work
RETRYABLE_REJECTS = (429, 503)


def backoff_delay(attempt, base_s=0.05, cap_s=2.0, retry_after_s=None,
                  jitter=None):
    """Delay before retry number ``attempt`` (0-based): capped
    exponential ``min(cap_s, base_s * 2**attempt)``, overridden upward
    by an explicit server ``Retry-After`` (the server knows its drain/
    quota window better than any client-side curve), plus optional
    jitter — ``jitter()`` in [0, 1) scales the delay by up to +100% so
    a synchronized client herd de-synchronizes.  Deterministic when
    ``jitter`` is None (unit tests pin the schedule)."""
    d = min(float(cap_s), float(base_s) * (2.0 ** int(attempt)))
    if retry_after_s is not None:
        d = max(d, float(retry_after_s))
    if jitter is not None:
        d *= 1.0 + float(jitter())
    return d


class ResponseDropped(RuntimeError):
    """The request was (or may have been) delivered but the connection
    died before its response arrived.  Deliberately NOT a
    ``ConnectionError``: callers gating on "no accepted response was
    dropped" (the bench SIGTERM-drain check) must see this as a drop,
    never as a clean connection refusal — and the client must never
    silently re-send a non-idempotent evaluate for it."""


class ServeClient:
    """One keep-alive connection to a service instance."""

    def __init__(self, host, port, client_id=None, timeout=300.0,
                 retries=None, backoff_base_s=0.05, backoff_cap_s=2.0,
                 jitter=True, sleep=time.sleep):
        self.host, self.port = host, int(port)
        self.client_id = client_id
        self.timeout = timeout
        #: 429/503 retry budget (flag-gated: default
        #: RAFT_TPU_SERVE_CLIENT_RETRIES, 0 = return rejections as-is)
        self.retries = (int(config.get("SERVE_CLIENT_RETRIES"))
                        if retries is None else int(retries))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self._jitter = random.random if jitter else None
        self._sleep = sleep
        self._conn = None
        #: response headers of the last completed round trip (the
        #: distributed-tracing tests read `traceparent` back from here)
        self.last_headers = {}
        #: parsed x-raft-provenance of the last response (None when the
        #: server sent no stamp): {bank_key, bank_sha, code, flags,
        #: replica} — WHAT produced the numbers, through the router too
        self.last_provenance = None

    def _connection(self):
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout)
        return self._conn

    def close(self):
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def request(self, method, path, payload=None, headers=None):
        """One logical request; returns ``(status_code, parsed_body)``
        — JSON-decoded when possible, raw text otherwise
        (``/metrics``).  With ``retries > 0``, clean 429/503
        rejections are re-sent after :func:`backoff_delay` (the
        server's ``Retry-After`` wins over the exponential curve)."""
        for attempt in range(self.retries + 1):
            status, body = self._round_trip(method, path, payload, headers)
            if status not in RETRYABLE_REJECTS or attempt >= self.retries:
                return status, body
            self._sleep(backoff_delay(
                attempt, self.backoff_base_s, self.backoff_cap_s,
                retry_after_s=self._retry_after(body),
                jitter=self._jitter))
        raise AssertionError("unreachable: retry loop always returns")

    def _retry_after(self, body):
        """The server's retry hint: the ``Retry-After`` header
        (integer seconds) or the payload's ``retry_after_s``."""
        ra = self.last_headers.get("retry-after")
        if ra is not None and str(ra).strip().isdigit():
            return float(ra)
        if isinstance(body, dict) and body.get("retry_after_s") is not None:
            try:
                return float(body["retry_after_s"])
            except (TypeError, ValueError):
                return None
        return None

    def _round_trip(self, method, path, payload=None, headers=None):
        """One wire round trip (no retry policy)."""
        body = None
        headers = dict(headers or {})
        if payload is not None:
            body = json.dumps(payload)
            headers["Content-Type"] = "application/json"
        if self.client_id:
            headers["X-Client"] = str(self.client_id)
        conn = self._connection()
        try:
            conn.request(method, path, body=body, headers=headers)
        except (http.client.HTTPException, ConnectionError, OSError):
            # SEND failed — the server never processed the request, so
            # one fresh-connection retry is safe even for POST (covers
            # the stale-keep-alive race)
            self.close()
            conn = self._connection()
            conn.request(method, path, body=body, headers=headers)
        try:
            resp = conn.getresponse()
            data = resp.read()
        except (http.client.HTTPException, ConnectionError, OSError) as e:
            # the request may have been ACCEPTED: re-sending would
            # duplicate a non-idempotent evaluation (and eat a second
            # quota token), and calling this a refusal would hide a
            # dropped response from the drain gate
            self.close()
            raise ResponseDropped(
                f"connection lost awaiting {method} {path}: {e!r}") from e
        self.last_headers = {k.lower(): v for k, v in resp.getheaders()}
        from raft_tpu.obs.alerts import parse_provenance

        self.last_provenance = parse_provenance(
            self.last_headers.get("x-raft-provenance"))
        if resp.will_close:
            self.close()
        try:
            return resp.status, json.loads(data)
        except ValueError:
            return resp.status, data.decode(errors="replace")

    def evaluate(self, design, Hs, Tp, beta, out_keys=None,
                 escalate_f64=False, traceparent=None):
        payload = {"design": design, "Hs": Hs, "Tp": Tp, "beta": beta}
        if out_keys:
            payload["out_keys"] = list(out_keys)
        if escalate_f64:
            payload["escalate_f64"] = True
        headers = {"traceparent": traceparent} if traceparent else None
        return self.request("POST", "/evaluate", payload, headers=headers)

    def healthz(self):
        return self.request("GET", "/healthz")

    def metrics_text(self):
        return self.request("GET", "/metrics")
