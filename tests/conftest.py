"""Test configuration: run JAX on a virtual 8-device CPU mesh in float64.

Correctness/parity tests run on CPU with x64 enabled so golden values
from the reference implementation (float64 numpy) can be matched to
tight tolerances; multi-chip sharding tests use the 8 virtual devices
(mirroring how the driver validates ``dryrun_multichip``).  TPU runs use
float32/bfloat16 via the benchmark path instead.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# NOTE: the axon TPU plugin in this image overrides JAX_PLATFORMS at import
# time, so the env var alone is not enough — set the config explicitly.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running parity/integration tests (excluded from the "
        "fast tier: pytest -m 'not slow')",
    )


import gc

import pytest


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled-executable caches after each test module.

    The full suite compiles hundreds of distinct programs (every design
    family x stage); on the CPU backend the accumulated executables can
    push the process into XLA compiler OOM segfaults late in the run.
    """
    yield
    jax.clear_caches()
    gc.collect()

REFERENCE_DIR = "/root/reference"
REF_TEST_DATA = os.path.join(REFERENCE_DIR, "tests", "test_data")


def ref_data(*parts):
    """Path into the reference's golden test-data directory (read-only)."""
    return os.path.join(REF_TEST_DATA, *parts)


@pytest.fixture(scope="session")
def native_bem_env():
    """Probe the native-BEM environment ONCE per session: the ctypes
    panel kernel (g++-compiled shared library) and the reference
    design/golden-data tree.  Returns ``{probe: reason}`` for every
    missing piece; tests that need a probe call
    :func:`require_native_env` and skip with the recorded reason — an
    environment gap is not a code regression and must not fail tier-1.
    """
    import shutil

    reasons = {}
    if shutil.which("g++") is None:
        reasons["native"] = "no C++ toolchain (g++ not on PATH)"
    else:
        try:
            from raft_tpu import native
            native._load()
        except Exception as e:  # build or ctypes load failure
            reasons["native"] = f"native panel kernel unavailable: {e}"
    if not os.path.isdir(REFERENCE_DIR):
        reasons["reference"] = (
            f"reference design/data tree unavailable ({REFERENCE_DIR})")
    return reasons


def require_native_env(reasons, *probes):
    """Skip the calling test when any needed env probe failed."""
    for probe in probes:
        if probe in reasons:
            pytest.skip(reasons[probe])
