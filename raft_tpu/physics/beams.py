"""Finite-element Timoshenko beam matrices for flexible members.

Twin of the reference's frame FE model
(``/root/reference/raft/raft_member.py``: ``computeStiffnessMatrix_FE``
:2154-2298, ``computeInertiaMatrix_FE`` :2300-2408): each element
between adjacent member nodes contributes a 12x12 stiffness/consistent-
mass matrix in the local (p1, p2, q) frame, rotated to global and
assembled into the member's (6 n_nodes) square matrices.

Evaluated in numpy at the reference pose (the build-time topology
pass); the assembled matrices enter the traced solves as constants.
"""

from __future__ import annotations

import numpy as np


def _section_props(mem, i):
    """Cross-section area / second moments of the element between nodes
    i and i+1 (mean of the node sections)."""
    if mem.circular:
        Do = 0.5 * (mem.dorsl_node_ext[i, 0] + mem.dorsl_node_ext[i + 1, 0])
        Di = 0.5 * (mem.dorsl_node_int[i, 0] + mem.dorsl_node_int[i + 1, 0])
        A = np.pi * (Do**2 - Di**2) / 4
        Jp1 = np.pi * (Do**4 - Di**4) / 64
        Jp2 = Jp1
        return A, Jp1, Jp2, Do, Di, None, None
    Wo = 0.5 * (mem.dorsl_node_ext[i] + mem.dorsl_node_ext[i + 1])
    Wi = 0.5 * (mem.dorsl_node_int[i] + mem.dorsl_node_int[i + 1])
    A = Wo[0] * Wo[1] - Wi[0] * Wi[1]
    Jp1 = (Wo[0] ** 3 * Wo[1] - Wi[0] ** 3 * Wi[1]) / 12
    Jp2 = (Wo[0] * Wo[1] ** 3 - Wi[0] * Wi[1] ** 3) / 12
    return A, Jp1, Jp2, None, None, Wo, Wi


def _rotation_12(mem):
    Dc_aux = np.column_stack((mem.p10, mem.p20, mem.q0))
    Dc = np.zeros((12, 12))
    for b in range(4):
        Dc[3 * b:3 * b + 3, 3 * b:3 * b + 3] = Dc_aux
    return Dc


def fe_stiffness(mem, node_r):
    """(6n, 6n) global-frame Timoshenko stiffness matrix of a beam
    member; node_r : (n, 3) current node positions."""
    n = len(node_r)
    K = np.zeros((6 * n, 6 * n))
    if mem.mtype != "beam":
        return K
    E, G = mem.E, mem.G
    nu = E / (2 * G) - 1
    Dc = _rotation_12(mem)

    for i in range(n - 1):
        L = np.linalg.norm(node_r[i + 1] - node_r[i])
        A, Jp1, Jp2, Do, Di, Wo, Wi = _section_props(mem, i)
        if mem.circular:
            ratio2 = (Di / Do) ** 2
            kp1 = (6 * (1 + nu) ** 2 * (1 + ratio2) ** 2) / (
                (1 + ratio2) ** 2 * (7 + 14 * nu + 8 * nu**2)
                + 4 * ratio2 * (5 + 10 * nu + 4 * nu**2))
            kp2 = kp1
            Jt = 2 * Jp1
        else:
            if Wi[0] == 0 or Wi[1] == 0:
                a, b = max(Wo), min(Wo)
                Jt = a * b**3 / 16 * (16 / 3 - 3.36 * (b / a) * (1 - b**4 / a**4 / 12))
                kp1 = 10 * (1 + nu) / (12 + 11 * nu)
                kp2 = kp1
            else:
                t0 = (Wo[0] - Wi[0]) / 2
                t1 = (Wo[1] - Wi[1]) / 2
                Jt = 2 * t0 * t1 * (Wo[0] - t0) ** 2 * (Wo[1] - t1) ** 2 / (
                    Wo[0] * t0 + Wo[1] * t1 - t0**2 - t1**2)

                m = Wi[0] * t1 / Wo[1] / t0
                nn = Wi[0] / Wo[1]
                kp1 = 10 * (1 + nu) * (1 + 3 * m) ** 2 / (
                    12 + 72 * m + 150 * m**2 + 90 * m**3
                    + nu * (11 + 66 * m + 135 * m**2 + 90 * m**3)
                    + 10 * nn**2 * ((3 + nu) * m + 3 * m**2))
                m = Wi[1] * t0 / Wo[0] / t1
                nn = Wi[1] / Wo[0]
                kp2 = 10 * (1 + nu) * (1 + 3 * m) ** 2 / (
                    12 + 72 * m + 150 * m**2 + 90 * m**3
                    + nu * (11 + 66 * m + 135 * m**2 + 90 * m**3)
                    + 10 * nn**2 * ((3 + nu) * m + 3 * m**2))

        Ksx = 12 * E * Jp2 / (G * kp1 * A * L**2)
        Ksy = 12 * E * Jp1 / (G * kp2 * A * L**2)

        K11 = np.zeros((6, 6))
        K11[0, 0] = 12 * E * Jp2 / L**3 / (1 + Ksx)
        K11[1, 1] = 12 * E * Jp1 / L**3 / (1 + Ksy)
        K11[2, 2] = E * A / L
        K11[3, 3] = (4 + Ksy) * E * Jp1 / L / (1 + Ksy)
        K11[4, 4] = (4 + Ksx) * E * Jp2 / L / (1 + Ksx)
        K11[5, 5] = G * Jt / L
        K11[0, 4] = 6 * E * Jp2 / L**2 / (1 + Ksx)
        K11[1, 3] = -6 * E * Jp1 / L**2 / (1 + Ksy)

        K22 = K11.copy()
        K22[0, 4] *= -1
        K22[1, 3] *= -1

        K12 = np.zeros((6, 6))
        K12[0, 0] = -K11[0, 0]
        K12[1, 1] = -K11[1, 1]
        K12[2, 2] = -K11[2, 2]
        K12[3, 3] = (2 - Ksy) * E * Jp1 / L / (1 + Ksy)
        K12[4, 4] = (2 - Ksx) * E * Jp2 / L / (1 + Ksx)
        K12[5, 5] = -K11[5, 5]
        K12[0, 4] = K11[0, 4]
        K12[1, 3] = K11[1, 3]
        K12[4, 0] = -K11[0, 4]
        K12[3, 1] = -K11[1, 3]

        K11 = K11 + K11.T - np.diag(K11.diagonal())
        K22 = K22 + K22.T - np.diag(K22.diagonal())
        Ke = np.block([[K11, K12], [K12.T, K22]])
        Keg = Dc @ Ke @ Dc.T
        K[6 * i:6 * i + 12, 6 * i:6 * i + 12] += Keg
    return K


def fe_inertia(mem, node_r):
    """(6n, 6n) global-frame consistent-mass matrix of a beam member."""
    n = len(node_r)
    M = np.zeros((6 * n, 6 * n))
    if mem.mtype != "beam":
        return M
    Dc = _rotation_12(mem)
    for i in range(n - 1):
        L = np.linalg.norm(node_r[i + 1] - node_r[i])
        A, Jp1, Jp2, *_ = _section_props(mem, i)
        Jz = Jp1 + Jp2

        M11 = np.zeros((6, 6))
        M11[0, 0] = 13 * A * L / 35 + 6 * Jp2 / 5 / L
        M11[1, 1] = 13 * A * L / 35 + 6 * Jp1 / 5 / L
        M11[2, 2] = A * L / 3
        M11[3, 3] = A * L**3 / 105 + 2 * L * Jp1 / 15
        M11[4, 4] = A * L**3 / 105 + 2 * L * Jp2 / 15
        M11[5, 5] = Jz * L / 3
        M11[0, 4] = 11 * A * L**2 / 210 + Jp2 / 10
        M11[1, 3] = -11 * A * L**2 / 210 - Jp1 / 10

        M22 = M11.copy()
        M22[0, 4] *= -1
        M22[1, 3] *= -1

        M12 = np.zeros((6, 6))
        M12[0, 0] = 9 * A * L / 70 - 6 * Jp2 / 5 / L
        M12[1, 1] = 9 * A * L / 70 - 6 * Jp1 / 5 / L
        M12[2, 2] = A * L / 6
        M12[3, 3] = -A * L**3 / 140 - L * Jp1 / 30
        M12[4, 4] = -A * L**3 / 140 - L * Jp2 / 30
        M12[5, 5] = Jz * L / 6
        M12[0, 4] = -13 * A * L**2 / 420 + Jp2 / 10
        M12[1, 3] = 13 * A * L**2 / 420 - Jp1 / 10
        M12[4, 0] = 13 * A * L**2 / 420 - Jp2 / 10
        M12[3, 1] = -13 * A * L**2 / 420 + Jp1 / 10

        M11 = M11 + M11.T - np.diag(M11.diagonal())
        M22 = M22 + M22.T - np.diag(M22.diagonal())
        Me = np.block([[M11, M12], [M12.T, M22]]) * mem.rho_shell
        Meg = Dc @ Me @ Dc.T
        M[6 * i:6 * i + 12, 6 * i:6 * i + 12] += Meg
    return M


def mass_and_center(M, node_r):
    """Mass and CG of a beam from its FE inertia matrix
    (helpers.py:1084-1125 getMassAndCenterOfBeam)."""
    n = len(node_r)
    nDOF = 6 * n
    X = np.zeros(nDOF)
    X[0::6] = 1
    mass = float(np.sum((M @ X) * X))
    center = np.zeros(3)
    if mass != 0:
        for ax in range(3):
            aux = np.zeros(nDOF)
            aux[ax::6] = 1
            rvec = np.zeros(nDOF)
            rvec[ax::6] = node_r[:, ax]
            center[ax] = np.sum(M @ (rvec) * aux) / mass
    return mass, center
