"""Deterministic fault injection for the sweep runtime.

Every failure path in :mod:`raft_tpu.parallel.resilience` — truncated
shard writes, transient evaluator errors, device OOM, NaN payloads,
unhealthy accelerator backends — can be triggered on demand so tests
exercise the *recovery* code, not just the happy path.  Faults are armed
either through the ``RAFT_TPU_FAULTS`` environment variable or the
:class:`inject` context manager; each armed fault fires a fixed number
of times and then disarms, which keeps injection deterministic (the
N-th retry after N-1 injected failures really succeeds).

Spec syntax (comma-separated in the env var, one string per spec in
``inject``)::

    kind:site[:count]          count defaults to 1

Kinds and the sites that consult them:

========== ================== ==============================================
kind       site               effect at the consulting site
========== ================== ==============================================
transient  shard_eval         raise :class:`TransientInjectedError`
oom        shard_eval         raise :class:`OOMInjectedError` (message
                              mimics an XLA ``RESOURCE_EXHAUSTED``)
truncate   shard_write        shard file is truncated after the atomic
                              rename, then :class:`InjectedFault` is raised
                              (simulates the process dying mid-write on a
                              filesystem that lost the tail)
nan        shard_result       first row of the computed shard is poisoned
                              with NaN
delay      shard_eval         sleep 0.25 s before the shard evaluation (a
                              deliberately slowed dispatch — the injected
                              perf regression `python -m raft_tpu.obs
                              runs regress` must catch; arm with a count
                              covering every shard)
unhealthy  backend_probe      ``probe_backend()`` reports the backend dead
worker_kill worker_shard      fabric worker SIGKILLs itself right after
                              claiming a shard lease (simulates a
                              preempted/OOM-killed host mid-shard; the
                              lease expires and the shard is stolen)
lease_expire lease_renew      fabric worker silently stops renewing its
                              leases (simulates a wedged-but-alive
                              process; stragglers get stolen while the
                              worker keeps computing)
replica_kill serve_evaluate   serving replica SIGKILLs itself on the
                              next /evaluate it routes (simulates a
                              replica dying mid-load; its fleet lease
                              expires, the router retries in-flight
                              requests onto the next ring replica and
                              evicts it)
replica_hang serve_evaluate   serving replica parks the next /evaluate
                              past every timeout (wedged-but-alive: the
                              router's per-attempt timeout fires and
                              fails the request over)
replica_5xx  serve_evaluate   serving replica answers the next
                              /evaluate with HTTP 500 (the retryable
                              failure class that drives the router's
                              circuit breaker without killing anything)
========== ================== ==============================================

The two worker-targeted kinds (``worker_kill``, ``lease_expire``) are
forwarded by the fabric coordinator to exactly ONE spawned worker
(index ``RAFT_TPU_FABRIC_FAULT_WORKER``, default 0) and stripped from
the rest — every worker arming ``worker_kill:worker_shard:1`` from a
shared environment would kill the whole fleet once each.  The three
replica-targeted kinds (``replica_*``) get the same treatment from the
fleet coordinator (``RAFT_TPU_FLEET_FAULT_REPLICA``).

Example::

    with faults.inject("transient:shard_eval:2"):
        run_sweep_checkpointed_full(...)   # first two evals fail, retries win

or, process-wide::

    RAFT_TPU_FAULTS=truncate:shard_write:1 python sweep_10k.py
"""

from __future__ import annotations

import os

from raft_tpu.utils import config


class InjectedFault(RuntimeError):
    """A non-transient injected failure (e.g. simulated crash mid-write)."""


class TransientInjectedError(RuntimeError):
    """An injected failure the retry layer must classify as transient."""


class OOMInjectedError(RuntimeError):
    """An injected failure that mimics an XLA device-OOM error string."""

    def __init__(self, msg="RESOURCE_EXHAUSTED: injected out of memory"):
        super().__init__(msg)


# armed faults: list of dicts {kind, site, count, env: bool}
_ACTIVE = []
_ENV_SEEN = None


def _parse(spec):
    parts = spec.strip().split(":")
    if len(parts) not in (2, 3) or not parts[0] or not parts[1]:
        raise ValueError(f"bad fault spec {spec!r} (want kind:site[:count])")
    count = int(parts[2]) if len(parts) == 3 else 1
    return {"kind": parts[0], "site": parts[1], "count": count}


def _sync_env():
    """(Re-)arm faults from RAFT_TPU_FAULTS whenever the var changes."""
    global _ENV_SEEN
    raw = config.raw("FAULTS") or ""
    if raw == _ENV_SEEN:
        return
    _ENV_SEEN = raw
    _ACTIVE[:] = [f for f in _ACTIVE if not f.get("env")]
    for spec in filter(None, (s.strip() for s in raw.split(","))):
        f = _parse(spec)
        f["env"] = True
        _ACTIVE.append(f)


def take(kind, site):
    """True when an armed ``kind:site`` fault should fire now.

    Decrements the matching fault's remaining count; a fault with no
    shots left never fires again (deterministic retry testing)."""
    _sync_env()
    for f in _ACTIVE:
        if f["kind"] == kind and f["site"] == site and f["count"] > 0:
            f["count"] -= 1
            return True
    return False


def check(site):
    """Raise whichever injected *error* fault is armed for ``site``.

    Consults the raising kinds (``transient``, ``oom``) so call sites
    need a single hook before doing real work."""
    if take("transient", site):
        raise TransientInjectedError(f"injected transient fault at {site}")
    if take("oom", site):
        raise OOMInjectedError()


class inject:
    """Context manager arming one or more fault specs for its scope::

        with faults.inject("nan:shard_result", "transient:shard_eval:2"):
            ...
    """

    def __init__(self, *specs):
        self._faults = [_parse(s) for s in specs]

    def __enter__(self):
        _ACTIVE.extend(self._faults)
        return self

    def __exit__(self, *exc):
        for f in self._faults:
            if f in _ACTIVE:
                _ACTIVE.remove(f)
        return False


def truncate_file(path, keep_fraction=0.5):
    """Truncate ``path`` to a fraction of its bytes (corrupt-shard sim)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, int(size * keep_fraction)))
