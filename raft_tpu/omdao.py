"""Design-optimization API: the WEIS/OpenMDAO-facing surface.

Equivalent of the reference's ``omdao_raft.RAFT_OMDAO``
(``/root/reference/raft/omdao_raft.py``: inputs :26-343, compute
:343-818, output mapping :820-887): one ``compute`` call is one design
evaluation — build the model, solve statics/dynamics over the case
table, and return flat outputs (platform properties, response
statistics, natural periods, WEIS aggregates).

Because the heavy path here is jit-compiled jax, an optimizer loop
amortizes compilation across iterations, and gradient-based optimizers
can switch to the differentiable design axis in
:func:`raft_tpu.api.make_design_evaluator` instead of finite
differences.

The OpenMDAO ``ExplicitComponent`` subclass is provided when openmdao
is importable (it is not part of this image); the dict-based
``DesignEvaluation`` below carries the same contract without the
dependency.
"""

from __future__ import annotations

import copy

import numpy as np


class DesignEvaluation:
    """One-design-in, flat-metrics-out evaluation for optimizer loops.

    Repeat calls on the SAME design (overrides None/unchanged) route
    through the traced full evaluator (``api.make_full_evaluator``) when
    the design permits — rigid single-FOWT, single wave heading per
    case — so an optimizer loop pays one jit compile, then milliseconds
    per evaluation instead of the orchestrated host path's seconds
    (VERDICT r4 Weak #7).  Arbitrary dotted-path overrides rebuild the
    model through the host path, which remains the oracle
    (tests/test_omdao.py pins evaluator-vs-host metric parity).

    Duplicate iterates don't even dispatch: the traced per-case outputs
    land in a content-addressed result cache
    (:class:`raft_tpu.serve.cache.ResultCache`, keyed by design hash +
    exact case bits), so an optimizer that revisits a corner — or a
    line search that re-evaluates its anchor point — gets the stored
    row back bit-identically instead of re-running the compiled
    program.  Hit/miss/byte totals are exposed on :attr:`diag` (and as
    ``omdao_cache_*`` metrics)."""

    def __init__(self, base_design, use_traced=True, cache_mb=None):
        import os

        from raft_tpu.serve.cache import ResultCache
        from raft_tpu.structure.schema import load_design
        from raft_tpu.utils import config

        # remember the source directory so relative data paths (MoorDyn
        # files, WAMIT coefficients) keep resolving after the design is
        # deep-copied as a dict
        self._base_dir = (os.path.dirname(os.path.abspath(base_design))
                          if isinstance(base_design, str) else None)
        self.base_design = load_design(base_design)
        self.use_traced = use_traced
        self._fast = None   # lazily: (model, jitted evaluate | None)
        if cache_mb is None:
            cache_mb = float(config.get("SERVE_CACHE_MB"))
        self._cache = ResultCache(int(cache_mb * 1e6),
                                  metrics_prefix="omdao_cache")
        self._design_fp = None  # lazily: content hash of base_design

    @property
    def diag(self):
        """Repeat-call diagnostics: result-cache counters (hits mean
        "this iterate never re-dispatched the compiled evaluator")."""
        return {f"cache_{k}": v for k, v in self._cache.stats().items()}

    # ---------------------------------------------------- traced path

    def _fast_model(self):
        """Cached (model, evaluate) for the base design; evaluate is
        None when the design is outside the traced evaluators' domain
        (multi-heading cases, potential-flow/QTF farms, ...), in which
        case the host path serves as the fallback."""
        if self._fast is not None:
            return self._fast
        import jax

        import raft_tpu
        from raft_tpu.api import (case_in_traced_domain, make_farm_evaluator,
                                  make_flexible_evaluator,
                                  make_full_evaluator)

        model = raft_tpu.Model(copy.deepcopy(self.base_design),
                               base_dir=self._base_dir)
        evaluate = None
        fs = model.fowtList[0]
        in_domain = all(case_in_traced_domain(c) for c in model.cases)
        if self.use_traced and in_domain:
            try:
                if model.nFOWT > 1:
                    evaluate = jax.jit(make_farm_evaluator(model))
                elif fs.is_single_body:
                    evaluate = jax.jit(make_full_evaluator(model))
                else:
                    evaluate = jax.jit(make_flexible_evaluator(model))
            except (AssertionError, ValueError):
                evaluate = None   # outside the traced domain: host path
        self._fast = (model, evaluate)
        return self._fast

    #: traced-evaluator outputs the metric chain consumes (and the
    #: result cache therefore stores per case)
    _CACHE_KEYS = ("X0", "Xi", "S", "zeta")

    def _evaluate_cached(self, evaluate, traced_case):
        """One traced-case dispatch through the result cache: duplicate
        optimizer iterates (identical design + case bits) return the
        stored row instead of re-running the compiled program."""
        from raft_tpu.aot.bank import content_fingerprint
        from raft_tpu.serve.cache import result_cache_key

        if self._design_fp is None:
            self._design_fp = content_fingerprint(self.base_design)
        key = result_cache_key(self._design_fp, traced_case,
                               self._CACHE_KEYS)
        row = self._cache.get(key)
        if row is None:
            out = evaluate(traced_case)
            row = {k: np.asarray(out[k]) for k in self._CACHE_KEYS}
            self._cache.put(key, row)
        return row

    def _compute_traced(self, model, evaluate):
        """Fill model.results['case_metrics'] from the traced evaluator:
        X0/Xi from the one-jit chain, channel statistics through the
        same turbine_outputs the host path uses."""
        from raft_tpu.api import case_to_traced
        from raft_tpu.models.outputs import turbine_outputs

        model.results = {"case_metrics": {}, "mean_offsets": []}
        offs = model.dof_offsets
        for iCase, case in enumerate(model.cases):
            out = self._evaluate_cached(evaluate, case_to_traced(case))
            X0 = np.asarray(out["X0"])
            Xi = np.asarray(out["Xi"])
            model.results["case_metrics"][iCase] = {}
            for i in range(model.nFOWT):
                tc = model.turbine_constants(case, ifowt=i)
                metrics = turbine_outputs(
                    model, case, X0[offs[i]:offs[i + 1]],
                    Xi[:, offs[i]:offs[i + 1], :],
                    np.asarray(out["S"]), np.asarray(out["zeta"]),
                    A_aero=np.asarray(tc["A00"]).T,
                    B_aero=np.asarray(tc["B00"]).T,
                    f_aero0=tc["f_aero0"], ifowt=i,
                    rotor_info=tc.get("rotor_info"))
                model.results["case_metrics"][iCase][i] = metrics
            model.results["mean_offsets"].append(X0)
        return model.results

    def compute(self, overrides=None):
        """Evaluate a design variant.

        overrides: dict of dotted design-path -> value, e.g.
        ``{"platform.members.0.d": [...], "mooring.lines.0.length": 870}``.
        Returns flat outputs (properties_*, per-case stats_*, periods,
        WEIS aggregates Max_Offset / Max_PtfmPitch).
        """
        import raft_tpu

        if not overrides:
            model, evaluate = self._fast_model()
            if evaluate is not None:
                self._compute_traced(model, evaluate)
            elif "case_metrics" not in getattr(model, "results", {}):
                model.analyze_cases()
        else:
            design = copy.deepcopy(self.base_design)
            for path, value in overrides.items():
                node = design
                keys = path.split(".")
                for k in keys[:-1]:
                    node = node[int(k)] if isinstance(node, list) else node[k]
                k = keys[-1]
                if isinstance(node, list):
                    node[int(k)] = value
                else:
                    node[k] = value

            model = raft_tpu.Model(design, base_dir=self._base_dir)
            model.analyze_cases()
        stat = model.statics(0)

        out = {
            # platform properties (omdao_raft.py:253-273)
            "properties_substructure_mass": float(stat["m_sub"]),
            "properties_total_mass": float(stat["m"]),
            "properties_displacement": float(stat["V"]),
            "properties_AWP": float(stat["AWP"]),
            "properties_center_of_mass": np.asarray(stat["rCG"]),
            "properties_center_of_buoyancy": np.asarray(stat["rCB"]),
            "properties_metacentric_height": float(stat["rM"][2] - stat["rCG"][2]),
        }

        # natural periods (omdao_raft.py:858-866); case-independent, so
        # cached per model instance for the repeat-call fast path
        fns = getattr(model, "_eigen_fns_cache", None)
        if fns is None:
            fns, _ = model.solve_eigen()
            model._eigen_fns_cache = np.asarray(fns)
        out["rigid_body_periods"] = 1.0 / np.maximum(np.asarray(fns), 1e-12)

        # per-case statistics + WEIS aggregates (omdao_raft.py:275-336)
        max_offset = 0.0
        max_pitch = 0.0
        for iCase, per_fowt in model.results["case_metrics"].items():
            for ifowt, m in per_fowt.items():
                for ch in ("surge", "sway", "heave", "roll", "pitch", "yaw"):
                    for s in ("avg", "std", "max"):
                        out[f"stats_{ch}_{s}_case{iCase}_fowt{ifowt}"] = float(
                            m[f"{ch}_{s}"])
                off = np.hypot(float(m["surge_max"]), float(m["sway_max"]))
                max_offset = max(max_offset, off)
                max_pitch = max(max_pitch, abs(float(m["pitch_max"])))
                if "Tmoor_avg" in m:
                    out[f"stats_Tmoor_max_case{iCase}_fowt{ifowt}"] = float(
                        np.max(np.asarray(m["Tmoor_max"])))
        out["Max_Offset"] = max_offset
        out["Max_PtfmPitch"] = max_pitch
        return out


class RAFT_OMDAO_Core:
    """The WEIS flat-I/O contract of the reference's RAFT_OMDAO
    (omdao_raft.py:14-887), implemented without the openmdao dependency:
    ~150 flat named inputs (turbine_*, rotor_*, platform_member{i}_*,
    mooring_*) are rebuilt into the nested design dict exactly as
    ``RAFT_OMDAO.compute`` does (:398-743), the model is analyzed, and
    the flat outputs (properties_*, stats_*, natural periods, WEIS
    aggregates Max_Offset / Max_PtfmPitch / Std_PtfmPitch /
    rotor_overspeed / max_nac_accel / max_tower_base) are produced
    (:820-887).

    Drive it with WEIS's own captured option/input YAMLs (the reference
    ships weis_options.yaml / weis_inputs.yaml generated by its
    DEBUG_OMDAO dump) or from an om.ExplicitComponent wrapper.
    """

    def __init__(self, modeling_options, analysis_options, turbine_options,
                 mooring_options, member_options):
        self.modeling_opt = modeling_options
        self.analysis_options = analysis_options
        self.turbine_opt = turbine_options
        self.mooring_opt = mooring_options
        self.members_opt = member_options

    # ------------------------------------------------------------- build
    def build_design(self, inputs, discrete_inputs=None):
        """Flat inputs -> nested design dict (omdao_raft.py:398-743)."""
        modeling_opt = self.modeling_opt
        turbine_opt = self.turbine_opt
        members_opt = self.members_opt
        mooring_opt = self.mooring_opt
        discrete_inputs = discrete_inputs or {}

        arr = lambda k: np.atleast_1d(np.asarray(inputs[k], dtype=float))
        arr2 = lambda k: np.asarray(inputs[k], dtype=float)
        val = lambda k: float(arr(k)[0])

        upwind = str(discrete_inputs.get(
            "rotor_orientation", inputs.get("rotor_orientation", "upwind"))
        ) == "upwind"
        sgn = -1.0 if upwind else 1.0

        design = {
            "type": ["input dictionary for RAFT"],
            "name": [self.analysis_options["general"]["fname_output"]],
            "comments": ["none"],
            "settings": {
                "XiStart": float(modeling_opt["xi_start"]),
                "min_freq": float(modeling_opt["min_freq"]),
                "max_freq": float(modeling_opt["max_freq"]),
                "nIter": int(modeling_opt["nIter"]),
            },
            "site": {
                "water_depth": val("mooring_water_depth"),
                "rho_air": val("rho_air"),
                "rho_water": val("rho_water"),
                "mu_air": val("mu_air"),
                "shearExp": val("shear_exp"),
            },
        }

        t = {
            "mRNA": val("turbine_mRNA"),
            "IxRNA": val("turbine_IxRNA"),
            "IrRNA": val("turbine_IrRNA"),
            "xCG_RNA": val("turbine_xCG_RNA"),
            "hHub": val("turbine_hHub"),
            "overhang": val("turbine_overhang") * sgn,
            "Fthrust": val("turbine_Fthrust"),
            "yaw_stiffness": val("turbine_yaw_stiffness"),
            "gear_ratio": val("gear_ratio"),
            "nBlades": int(discrete_inputs.get("nBlades", inputs.get("nBlades", 3))),
            "shaft_tilt": val("tilt") * sgn,
            "precone": val("precone") * sgn,
            "Zhub": val("wind_reference_height"),
            "Rhub": val("hub_radius"),
            "I_drivetrain": val("rotor_inertia"),
        }
        design["turbine"] = t

        # tower member (rA below rB; flip for MHK)
        rA = arr2("turbine_tower_rA")
        rB = arr2("turbine_tower_rB")
        if rA[2] > rB[2]:
            rA, rB = rB, rA
        tow = {
            "name": "tower", "type": "rigid", "rA": rA, "rB": rB,
            "shape": turbine_opt["shape"],
            "gamma": val("turbine_tower_gamma"),
            "stations": arr2("turbine_tower_stations"),
            "rho_shell": val("turbine_tower_rho_shell"),
        }
        for key, scalar in (("d", turbine_opt["scalar_diameters"]),
                            ("t", turbine_opt["scalar_thicknesses"])):
            v = arr2(f"turbine_tower_{key}")
            tow[key] = float(np.atleast_1d(v)[0]) if scalar else v
        for key in ("Cd", "Ca", "CdEnd", "CaEnd"):
            v = arr2(f"turbine_tower_{key}")
            tow[key] = (float(np.atleast_1d(v)[0])
                        if turbine_opt["scalar_coefficients"] else v)
        t["tower"] = tow

        # blade + airfoils
        t["blade"] = {
            "geometry": np.c_[arr2("blade_r"), arr2("blade_chord"),
                              arr2("blade_theta"), arr2("blade_precurve"),
                              arr2("blade_presweep")],
            "Rtip": val("blade_Rtip"),
            "precurveTip": val("blade_precurveTip"),
            "presweepTip": val("blade_presweepTip"),
            "airfoils": [
                [float(ap), nm] for ap, nm in zip(
                    np.atleast_1d(arr2("airfoils_position")),
                    turbine_opt["af_used_names"])],
        }
        n_af = turbine_opt["n_af"]
        # polars may arrive as (n_af, n_aoa, n_Re) or squeezed (n_af, n_aoa)
        tab3 = lambda k: np.asarray(inputs[k], dtype=float).reshape(
            n_af, -1)[:, :len(arr2("airfoils_aoa"))]
        cl = tab3("airfoils_cl")
        cd = tab3("airfoils_cd")
        cm = tab3("airfoils_cm")
        aoa = arr2("airfoils_aoa")
        t["airfoils"] = []
        for i in range(n_af):
            t["airfoils"].append({
                "name": turbine_opt["af_used_names"][i],
                "relative_thickness": float(arr2("airfoils_r_thick")[i]),
                "data": np.c_[aoa, cl[i], cd[i], cm[i]],
            })

        t["pitch_control"] = {
            "GS_Angles": arr2("rotor_PC_GS_angles"),
            "GS_Kp": arr2("rotor_PC_GS_Kp"),
            "GS_Ki": arr2("rotor_PC_GS_Ki"),
            "Fl_Kp": val("Fl_Kp"),
        }
        t["torque_control"] = {"VS_KP": val("rotor_TC_VS_Kp"),
                               "VS_KI": val("rotor_TC_VS_Ki")}
        t["wt_ops"] = {
            "v": arr2("rotor_powercurve_v"),
            "omega_op": arr2("rotor_powercurve_omega_rpm"),
            "pitch_op": arr2("rotor_powercurve_pitch"),
        }

        # platform members (ghost-segment trimming, omdao_raft.py:548-686)
        p = {"potModMaster": int(modeling_opt["potential_model_override"]),
             "dlsMax": float(modeling_opt["dls_max"])}
        if p["potModMaster"] == 3:
            p["potFirstOrder"] = 1
            p["hydroPath"] = modeling_opt["BEM_dir"]
        design["platform"] = p
        nmembers = members_opt["nmembers"]
        member_shapes = members_opt["shape"]
        scalar_d = members_opt["scalar_diameters"]
        scalar_t = members_opt["scalar_thicknesses"]
        scalar_c = members_opt["scalar_coefficients"]
        p["members"] = []
        for i in range(nmembers):
            mn = f"platform_member{i+1}_"
            shape = member_shapes[i]
            rA_0 = arr2(mn + "rA")
            rB_0 = arr2(mn + "rB")
            sA = val(mn + "s_ghostA")
            sB = val(mn + "s_ghostB")
            s_0 = arr2(mn + "stations")
            idx = np.logical_and(s_0 >= sA, s_0 <= sB)
            s_grid = np.unique(np.r_[sA, s_0[idx], sB])
            rA = rA_0 + sA * (rB_0 - rA_0)
            rB = rA_0 + sB * (rB_0 - rA_0)
            m = {
                "name": mn, "type": "rigid", "rA": rA, "rB": rB,
                "extensionA": float(np.linalg.norm(rA_0 - rA)),
                "extensionB": float(np.linalg.norm(rB_0 - rB)),
                "shape": shape, "gamma": val(mn + "gamma"),
                "potMod": members_opt[mn + "potMod"],
                "stations": s_grid,
                "rho_shell": val(mn + "rho_shell"),
            }
            if shape in ("circ", "square"):
                m["d"] = ([val(mn + "d")] * len(s_grid) if scalar_d[i]
                          else np.interp(s_grid, s_0, arr2(mn + "d")))
            else:
                dv = arr2(mn + "d")
                d2 = np.zeros((len(s_grid), 2))
                if scalar_d[i]:
                    d2[:, 0], d2[:, 1] = dv[0], dv[1]
                else:
                    d2[:, 0] = np.interp(s_grid, s_0, dv[:, 0])
                    d2[:, 1] = np.interp(s_grid, s_0, dv[:, 1])
                m["d"] = d2
            m["t"] = (val(mn + "t") if scalar_t[i]
                      else np.interp(s_grid, s_0, arr2(mn + "t")))
            for cname in ("Cd", "Ca"):
                cv = arr2(mn + cname)
                if shape == "circ":
                    m[cname] = (float(np.atleast_1d(cv)[0]) if scalar_c[i]
                                else np.interp(s_grid, s_0, cv))
                else:
                    if scalar_c[i]:
                        m[cname] = [float(cv[0]), float(cv[1])]
                    else:
                        c2 = np.zeros((len(s_grid), 2))
                        c2[:, 0] = np.interp(s_grid, s_0, cv[:, 0])
                        c2[:, 1] = np.interp(s_grid, s_0, cv[:, 1])
                        m[cname] = c2
            for cname in ("CdEnd", "CaEnd"):
                cv = arr2(mn + cname)
                m[cname] = (float(np.atleast_1d(cv)[0]) if scalar_c[i]
                            else np.interp(s_grid, s_0, cv))
            if members_opt["nreps"][i] > 0:
                m["heading"] = arr2(mn + "heading")
            if members_opt["npts_lfill"][i] > 0:
                m["l_fill"] = arr2(mn + "l_fill")
                m["rho_fill"] = arr2(mn + "rho_fill")
            # bulkheads / ring stiffeners -> cap stations
            #  (omdao_raft.py:646-686)
            ncaps = members_opt["ncaps"][i]
            ring_spacing = val(mn + "ring_spacing") if mn + "ring_spacing" in inputs else 0.0
            if ncaps > 0 or ring_spacing > 0:
                s_height = s_grid[-1] - s_grid[0]
                n_stiff = 0 if ring_spacing == 0.0 else int(np.floor(s_height / ring_spacing))
                s_ring = (np.arange(1, n_stiff + 0.1) - 0.5) * (ring_spacing / s_height)
                s_cap_0 = arr2(mn + "cap_stations")
                idx_cap = np.logical_and(s_cap_0 >= sA, s_cap_0 <= sB)
                s_cap, isort = np.unique(np.r_[sA, s_cap_0[idx_cap], sB],
                                         return_index=True)
                t_all = arr2(mn + "cap_t")
                t_cap = np.r_[t_all[0], t_all[idx_cap], t_all[-1]][isort]
                d_arr = np.asarray(m["d"])
                rect = d_arr.ndim == 2
                # rect members carry (side_x, side_y) hole pairs
                di_cap = np.zeros((len(s_cap), 2) if rect else s_cap.shape)
                if sA > 0.0:
                    s_cap, t_cap, di_cap = s_cap[1:], t_cap[1:], di_cap[1:]
                if sB < 1.0:
                    s_cap, t_cap, di_cap = s_cap[:-1], t_cap[:-1], di_cap[:-1]
                if np.any(s_ring):
                    if rect:
                        # per-side interpolation (the reference intends
                        # this for rect members, omdao_raft.py:653-656)
                        d_ring = np.stack(
                            [np.interp(s_ring, s_grid, d_arr[:, j])
                             for j in range(2)], axis=1)
                    else:
                        d_ring = np.interp(s_ring, s_grid, d_arr)
                    s_cap = np.r_[s_ring, s_cap]
                    t_cap = np.r_[val(mn + "ring_t") * np.ones(n_stiff), t_cap]
                    di_cap = np.concatenate(
                        [d_ring - 2 * val(mn + "ring_h"), di_cap], axis=0)
                if len(s_cap) > 0:
                    isort = np.argsort(s_cap)
                    m["cap_stations"] = s_cap[isort]
                    m["cap_t"] = t_cap[isort]
                    m["cap_d_in"] = di_cap[isort]
            p["members"].append(m)

        # rigid bodies -> point inertias
        rb = (modeling_opt.get("floating", {}) or {}).get(
            "rigid_bodies", {"n_bodies": 0})
        add = []
        for k in range(rb.get("n_bodies", 0)):
            add.append({
                "type": "point_inertia",
                "location": arr2(f"rigid_body_{k}_node"),
                "mass": val(f"rigid_body_{k}_mass"),
                "moments_of_inertia": np.r_[arr2(f"rigid_body_{k}_inertia"),
                                            0.0, 0.0, 0.0],
            })
        if add:
            p["additional_effects"] = add

        # mooring
        mo = {"water_depth": val("mooring_water_depth"), "points": [],
              "lines": [], "line_types": []}
        for i in range(mooring_opt["nconnections"]):
            pn = f"mooring_point{i+1}_"
            pt = {"name": mooring_opt[pn + "name"],
                  "type": mooring_opt[pn + "type"],
                  "location": arr2(pn + "location")}
            if pt["type"].lower() == "fixed":
                pt["anchor_type"] = "drag_embedment"
            mo["points"].append(pt)
        for i in range(mooring_opt["nlines"]):
            ln = f"mooring_line{i+1}_"
            mo["lines"].append({
                "name": f"line{i+1}",
                "endA": mooring_opt[ln + "endA"],
                "endB": mooring_opt[ln + "endB"],
                "type": mooring_opt[ln + "type"],
                "length": val(ln + "length"),
            })
        for i in range(mooring_opt["nline_types"]):
            lt = f"mooring_line_type{i+1}_"
            mo["line_types"].append({
                "name": mooring_opt[lt + "name"],
                "diameter": val(lt + "diameter"),
                "mass_density": val(lt + "mass_density"),
                "stiffness": val(lt + "stiffness"),
                "breaking_load": val(lt + "breaking_load"),
                "cost": val(lt + "cost"),
                "transverse_added_mass": val(lt + "transverse_added_mass"),
                "tangential_added_mass": val(lt + "tangential_added_mass"),
                "transverse_drag": val(lt + "transverse_drag"),
                "tangential_drag": val(lt + "tangential_drag"),
            })
        mo["anchor_types"] = [{"name": "drag_embedment", "mass": 1e3,
                               "cost": 1e4, "max_vertical_load": 0.0,
                               "max_lateral_load": 1e5}]
        design["mooring"] = mo

        # DLC cases: spectral-wind cases only (omdao_raft.py:725-733)
        keys = modeling_opt["raft_dlcs_keys"]
        turb_ind = keys.index("turbulence")
        data = [cd for cd in modeling_opt["raft_dlcs"]
                if any(tt in str(cd[turb_ind]) for tt in ("NTM", "ETM", "EWM"))]
        design["cases"] = {"keys": keys, "data": data}
        self.case_mask = [
            any(tt in str(cd[turb_ind]) for tt in ("NTM", "ETM", "EWM"))
            for cd in modeling_opt["raft_dlcs"]]
        return design

    # ----------------------------------------------------------- compute
    def compute(self, inputs, discrete_inputs=None):
        """One design evaluation: build -> analyze -> flat outputs
        (omdao_raft.py:743-887)."""
        import raft_tpu

        design = self.build_design(inputs, discrete_inputs)
        model = raft_tpu.Model(design)
        model.solve_statics(None)
        model.analyze_cases()
        results = model.calc_outputs()

        outputs = {}
        for name, v in results["properties"].items():
            outputs["properties_" + name] = v

        names = ["surge", "sway", "heave", "roll", "pitch", "yaw",
                 "AxRNA", "Mbase", "Tmoor"]
        stats = ["avg", "std", "max", "PSD"]
        case_metrics = [cm[0] for cm in results["case_metrics"].values()]
        for n in names:
            for s in stats:
                outputs[f"stats_{n}_{s}"] = np.squeeze(np.array(
                    [np.asarray(cm[f"{n}_{s}"]) for cm in case_metrics]))
        for n in ("omega", "torque", "power", "bPitch"):
            key = f"{n}_max" if n == "omega" else f"{n}_avg"
            if key in case_metrics[0]:
                for s in stats:
                    k2 = f"{n}_{s}"
                    if k2 in case_metrics[0]:
                        outputs[f"stats_{k2}"] = np.squeeze(np.array(
                            [np.asarray(cm[k2]) for cm in case_metrics]))

        fns = np.asarray(results["eigen"]["frequencies"])
        outputs["rigid_body_periods"] = 1.0 / np.maximum(fns, 1e-12)
        for i, n in enumerate(("surge", "sway", "heave", "roll", "pitch", "yaw")):
            outputs[f"{n}_period"] = float(outputs["rigid_body_periods"][i])

        # WEIS aggregates (omdao_raft.py:869-880)
        outputs["Max_Offset"] = float(np.max(np.sqrt(
            np.atleast_1d(outputs["stats_surge_max"]) ** 2
            + np.atleast_1d(outputs["stats_sway_max"]) ** 2)))
        outputs["heave_avg"] = float(np.mean(outputs["stats_heave_avg"]))
        outputs["Max_PtfmPitch"] = float(np.max(outputs["stats_pitch_max"]))
        outputs["Std_PtfmPitch"] = float(np.mean(outputs["stats_pitch_std"]))
        outputs["max_nac_accel"] = float(np.max(outputs["stats_AxRNA_max"]))
        outputs["max_tower_base"] = float(np.max(outputs["stats_Mbase_max"]))
        if "stats_omega_max" in outputs and "rated_rotor_speed" in inputs:
            rated = float(np.atleast_1d(np.asarray(
                inputs["rated_rotor_speed"], dtype=float))[0])
            if rated > 0:
                outputs["rotor_overspeed"] = float(
                    (np.max(outputs["stats_omega_max"]) - rated) / rated)

        outputs["platform_displacement"] = outputs["properties_buoyancy (pgV)"] \
            / (design["site"]["rho_water"] * 9.81)
        outputs["platform_mass"] = outputs["properties_substructure mass"]
        outputs["platform_total_center_of_mass"] = outputs[
            "properties_substructure CG"]
        outputs["platform_I_total"] = np.r_[
            outputs["properties_roll inertia at subCG"],
            outputs["properties_pitch inertia at subCG"],
            outputs["properties_yaw inertia at subCG"], 0.0, 0.0, 0.0]
        return outputs


try:  # OpenMDAO component wrapper (optional dependency)
    import openmdao.api as om

    class RAFT_TPU_Component(om.ExplicitComponent):
        """ExplicitComponent exposing DesignEvaluation to WEIS-style
        optimization problems (omdao_raft.RAFT_OMDAO analog)."""

        def initialize(self):
            self.options.declare("base_design")
            self.options.declare("design_vars", types=dict,
                                 desc="input name -> dotted design path")
            self.options.declare("outputs", types=list)

        def setup(self):
            self._eval = DesignEvaluation(self.options["base_design"])
            for name in self.options["design_vars"]:
                self.add_input(name)
            for name in self.options["outputs"]:
                self.add_output(name)

        def compute(self, inputs, outputs):
            overrides = {
                path: float(inputs[name])
                for name, path in self.options["design_vars"].items()
            }
            res = self._eval.compute(overrides)
            for name in self.options["outputs"]:
                outputs[name] = res[name]

except ImportError:  # pragma: no cover - openmdao absent in this image
    RAFT_TPU_Component = None
