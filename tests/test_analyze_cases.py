"""End-to-end analyzeCases parity (no-wind cases) vs reference goldens.

Exercises the full chain: statics -> mooring equilibrium -> wave
excitation -> iterative drag linearisation -> impedance solve ->
response statistics, against *_true_analyzeCases.pkl.

Only cases with wind_speed == 0 are compared until the aero module
lands (wind cases additionally need rotor thrust/damping).
"""

import os
import pickle

import numpy as np
import pytest
from numpy.testing import assert_allclose

from tests.conftest import ref_data

import raft_tpu

pytestmark = pytest.mark.slow

METRICS = [
    "wave_PSD", "surge_PSD", "sway_PSD", "heave_PSD", "roll_PSD",
    "pitch_PSD", "yaw_PSD", "AxRNA_PSD", "Mbase_PSD", "Tmoor_PSD",
]


def test_analyze_cases_oc3_nowind():
    path = ref_data("OC3spar.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    res = model.analyze_cases()
    with open(path.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)

    # case 0 has wind_speed == 0 (no aero); case 1 needs the aero module
    iCase = 0
    assert model.cases[iCase]["wind_speed"] == 0
    for metric in METRICS:
        a = np.asarray(res["case_metrics"][iCase][0][metric])
        b = np.asarray(true["case_metrics"][iCase][0][metric])
        if metric == "Tmoor_PSD":
            # the reference's tension spectra inherit MoorPy's coarse
            # 0.1-step finite-difference tension Jacobian (including a
            # 0.1 *rad* rotational step); we replicate the secant but
            # small catenary-model differences remain visible at ~3e-5
            assert_allclose(a, b, rtol=3e-5, atol=1e-3, err_msg=metric)
        else:
            assert_allclose(a, b, rtol=1e-5, atol=1e-3, err_msg=metric)
