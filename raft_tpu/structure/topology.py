"""Build-time structural topology: joints, rigid links, DOF reduction.

The FOWT is a graph of 6-DOF nodes (one per rigid member, rotor, or
joint anchor) connected by joints (cantilever / ball / universal) and
rigid links.  A breadth-first traversal assigns each node a set of
*reduced* DOFs and a linear map ``T_aux`` from those reduced DOFs to the
node's 6 DOFs; stacking gives the structure transformation matrix
``T (nFullDOF x nDOF)`` with ``fullDOF = T @ reducedDOF``.

This re-derives the reference's reduction machinery
(``/root/reference/raft/raft_fowt.py``: ``addJoint`` :439,
``attachMemberToJoint`` :477, ``reduceDOF`` :553,
``computeTransformationMatrix`` :624,
``computeDerivativeTransformationMatrix`` :640, and
``/root/reference/raft/raft_node.py`` ``attachToNode`` :79-159) with one
simplification: where the reference materialises two dummy nodes per
offset attachment (joint-anchor + member-side) connected by a rigid
link, we keep a single anchor node per joint and apply the rigid-link
shift ``H(r_node - r_anchor)`` directly — algebraically identical for
the resulting reduced system since dummy nodes carry no mass.

Everything here is numpy and runs once per design at build time.  The
kinematic chain (root, link offsets) is exported so the traced physics
can recompute T under mean offsets (T depends on *current* node
positions; see fowt.setPosition -> reduceDOF, raft_fowt.py:774).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


def _getH(r):
    return np.array(
        [[0.0, r[2], -r[1]], [-r[2], 0.0, r[0]], [r[1], -r[0], 0.0]]
    )


@dataclass
class TopoNode:
    id: int
    r0: np.ndarray                 # reference position wrt PRP (3,)
    kind: str                      # 'member' | 'rotor' | 'anchor'
    owner: int = -1                # member or rotor index
    end_node: bool = True          # False for internal beam nodes
    joint_id: int | None = None
    joint_type: str | None = None
    rigid_partner: int | None = None   # node id connected by a rigid link
    # traversal state
    reducedDOF: list = field(default_factory=list)
    T_aux: np.ndarray | None = None
    parent: int | None = None


class Topology:
    """Node graph + DOF reduction for one FOWT."""

    def __init__(self):
        self.nodes: list[TopoNode] = []
        self.joints: list[dict] = []
        self._links: list[tuple[int, int]] = []
        self._chains: list[list[int]] = []  # beam member node chains

    # ---------------------------------------------------------- build
    def add_node(self, r0, kind, owner=-1, end_node=True):
        n = TopoNode(id=len(self.nodes), r0=np.array(r0, dtype=float), kind=kind,
                     owner=owner, end_node=end_node)
        self.nodes.append(n)
        return n

    def add_chain(self, node_ids):
        """Register a flexible member's node chain: internal nodes own
        their DOFs; traversal reaches them through the chain (the BFS
        beam handling of raft_fowt.py:601-605)."""
        self._chains.append(list(node_ids))

    def add_joint(self, r, jtype, name, tol=1e-3):
        """Create (or reuse, by name+position) a joint; raft_fowt.py:439-475."""
        r = np.asarray(r, dtype=float)
        for j in self.joints:
            if j["name"] == name and np.linalg.norm(j["r"] - r) <= tol:
                return j
        j = {"id": len(self.joints), "r": r.copy(), "type": jtype, "name": name}
        self.joints.append(j)
        return j

    def attach_node_to_joint(self, node: TopoNode, joint, tol=1e-3):
        """raft_fowt.py:477-551 with the single-anchor simplification."""
        dist = np.linalg.norm(node.r0 - joint["r"])
        if dist <= tol:
            node.joint_id = joint["id"]
            node.joint_type = joint["type"]
            return
        # offset attachment: anchor node at the joint + rigid link
        anchor = None
        for n in self.nodes:
            if n.kind == "anchor" and n.joint_id == joint["id"]:
                anchor = n
                break
        if anchor is None:
            anchor = self.add_node(joint["r"], "anchor")
            anchor.joint_id = joint["id"]
            anchor.joint_type = joint["type"]
        # a rigid link can only pair two nodes; chain through the member
        # node (a node may carry several links in general — keep a list)
        self._links.append((anchor.id, node.id))

    # ------------------------------------------------------- traversal
    def reduce(self, positions=None):
        """Assign reduced DOFs via BFS from the root node and build T.

        positions: optional (n_nodes, 3) current node positions (defaults
        to reference positions) — T depends on them through the rigid
        link offsets (raft_node.py:113-118).

        Returns (T, reducedDOF, root_id).
        """
        nodes = self.nodes
        r = (
            np.array([n.r0 for n in nodes])
            if positions is None
            else np.asarray(positions, dtype=float)
        )

        for n in nodes:
            n.reducedDOF = None
            n.T_aux = None
            n.parent = None

        # root: node closest to the origin (raft_fowt.py:315-318)
        root = min(nodes, key=lambda n: np.linalg.norm(n.r0))

        links_by_node: dict[int, list[int]] = {}
        for a, b in self._links:
            links_by_node.setdefault(a, []).append(b)
            links_by_node.setdefault(b, []).append(a)

        joint_groups: dict[int, list[int]] = {}
        for n in nodes:
            if n.joint_id is not None:
                joint_groups.setdefault(n.joint_id, []).append(n.id)

        def attach(child: TopoNode, parent: TopoNode, rigid_link: bool):
            """raft_node.py:79-159 (open-tree branches)."""
            assert child.end_node, "only end nodes attach via joints/links"
            dofs = [list(d) for d in parent.reducedDOF]
            T2 = parent.T_aux.copy()
            jt = "rigid_link" if rigid_link else child.joint_type
            if jt == "rigid_link":
                rot = parent.T_aux[3:6, :]
                T2 = T2.copy()
                T2[:3, :] = T2[:3, :] + _getH(r[child.id] - r[parent.id]) @ rot
            elif jt in ("ball", "universal"):
                T2 = np.hstack([T2, np.zeros((6, 3))])
                T2[3:6, :] = 0.0
                for idof in range(3, 6):
                    dofs.append([child.id, idof])
                    T2[idof, len(dofs) - 1] = 1.0
                keep = [i for i in range(T2.shape[1]) if np.any(T2[:, i] != 0)]
                T2 = T2[:, keep]
                dofs = [dofs[i] for i in keep]
            elif jt == "cantilever":
                pass
            else:
                raise ValueError(f"joint type {jt!r} not supported")
            order = sorted(range(len(dofs)), key=lambda i: (dofs[i][0], dofs[i][1]))
            child.reducedDOF = [dofs[i] for i in order]
            child.T_aux = T2[:, order]
            child.parent = parent.id

        chains_by_node: dict[int, list[int]] = {}
        for chain in self._chains:
            for nid in chain:
                chains_by_node[nid] = chain

        root.reducedDOF = [[root.id, i] for i in range(6)]
        root.T_aux = np.eye(6)
        root.parent = root.id
        visited = {root.id}
        queue = [root]
        while queue:
            node = queue.pop(0)
            # unattached nodes reached through a beam chain get their own
            # identity DOFs (raft_fowt.py:577-584)
            if node.reducedDOF is None:
                node.reducedDOF = [[node.id, i] for i in range(6)]
                node.T_aux = np.eye(6)
                node.parent = node.id
            for pid in links_by_node.get(node.id, []):
                p = nodes[pid]
                if p.id not in visited:
                    attach(p, node, rigid_link=True)
                    visited.add(p.id)
                    queue.append(p)
            if node.joint_id is not None:
                for nid in joint_groups.get(node.joint_id, []):
                    nn = nodes[nid]
                    if nn.id not in visited:
                        attach(nn, node, rigid_link=False)
                        visited.add(nn.id)
                        queue.append(nn)
            # traverse beam chains from their end nodes
            if node.end_node and node.id in chains_by_node:
                for nid in chains_by_node[node.id]:
                    if nid not in visited:
                        visited.add(nid)
                        queue.append(nodes[nid])

        if len(visited) != len(nodes):
            missing = [n.id for n in nodes if n.id not in visited]
            raise RuntimeError(f"structure not fully connected; unreached nodes {missing}")

        # collect unique DOFs with the root node first (the reference
        # moves the rigid-body node to the front of nodeList,
        # raft_fowt.py:321-328)
        reducedDOF = []
        for n in [root] + [x for x in nodes if x.id != root.id]:
            for d in n.reducedDOF:
                if d not in reducedDOF:
                    reducedDOF.append(d)

        nDOF = len(reducedDOF)
        T = np.zeros((6 * len(nodes), nDOF))
        for n in nodes:
            for jcol, d in enumerate(n.reducedDOF):
                T[6 * n.id : 6 * n.id + 6, reducedDOF.index(d)] = n.T_aux[:, jcol]
        return T, reducedDOF, root.id

    def displacements(self, T, reducedDOF, root_id, Xi0):
        """Nonlinear mean node displacements (n_nodes, 6) for reduced
        displacements Xi0 — the setNodesPosition nonlinear path
        (raft_fowt.py:669-752): rigid links rotate exactly
        ((R(theta) - I) d), ball joints keep their own linear rotation,
        beam chains get linear displacements plus the end-node's
        nonlinear-minus-linear correction.  Preserves rigid link lengths
        at large mean rotations (the displaced-pose statics of
        flexible/multibody structures need this).

        NOTE the linear map ``T`` is an input: the reference evaluates
        setDisplacementLinear with each node's *current* T (recomputed
        by reduceDOF at the latest node positions), so at a converged
        mean pose the kinematics satisfy the self-consistency
        T* = reduce(positions(T*, Xi0)) — see
        :func:`self_consistent_displacements`."""
        Xi0 = np.asarray(Xi0, dtype=float)
        nodes = self.nodes
        n = len(nodes)
        lin = (np.asarray(T) @ Xi0).reshape(n, 6)
        disp = np.full((n, 6), np.nan)

        def rotmat(th):
            from raft_tpu.ops import transforms as tf
            import jax.numpy as jnp

            return np.asarray(tf.rotation_matrix(th[0], th[1], th[2]))

        links_by_node: dict[int, list[int]] = {}
        for a, b in self._links:
            links_by_node.setdefault(a, []).append(b)
            links_by_node.setdefault(b, []).append(a)
        joint_groups: dict[int, list[int]] = {}
        for nd in nodes:
            if nd.joint_id is not None:
                joint_groups.setdefault(nd.joint_id, []).append(nd.id)
        chains_by_node: dict[int, list[int]] = {}
        for chain in self._chains:
            for nid in chain:
                chains_by_node[nid] = chain

        root = nodes[root_id]
        disp[root.id] = lin[root.id]
        visited = {root.id}
        queue = [root]
        while queue:
            node = queue.pop(0)
            # rigid-link partners: exact rotation of the offset
            for pid in links_by_node.get(node.id, []):
                p = nodes[pid]
                if p.id in visited:
                    continue
                d = p.r0 - node.r0
                R = rotmat(lin[node.id][3:])
                disp[p.id] = disp[node.id].copy()
                disp[p.id][:3] += (R - np.eye(3)) @ d
                visited.add(p.id)
                queue.append(p)
            # joint-connected nodes: same translation; ball/universal
            # joints keep their own (linear) rotation
            if node.joint_id is not None:
                for nid in joint_groups.get(node.joint_id, []):
                    nn = nodes[nid]
                    if nn.id in visited:
                        continue
                    disp[nn.id] = disp[node.id].copy()
                    # the reference overrides the rotation only for ball
                    # joints (raft_fowt.py:731-733)
                    if nn.joint_type == "ball":
                        disp[nn.id][3:] = lin[nn.id][3:]
                    visited.add(nn.id)
                    queue.append(nn)
            # beam chains: linear + the end node's nonlinear correction
            if node.end_node and node.id in chains_by_node:
                dR = disp[node.id] - lin[node.id]
                for nid in chains_by_node[node.id]:
                    if nid in visited:
                        continue
                    disp[nid] = lin[nid] + dR
                    visited.add(nid)
                    queue.append(nodes[nid])
        # any unreached node (shouldn't happen on a connected structure)
        # falls back to the linear map
        missing = np.isnan(disp[:, 0])
        disp[missing] = lin[missing]
        return disp

    def self_consistent_displacements(self, T0, reducedDOF, root_id, Xi0,
                                      n_iter=1, atol=1e-13):
        """Displacements + T of the displaced pose with ``n_iter`` lag
        updates of the node-displacement map.

        The reference's solveStatics calls setPosition at every solver
        evaluation; each call computes node displacements with the T of
        the *previous* reduceDOF and then recomputes T at the new
        positions (raft_fowt.py:753-780).  Its published equilibria
        correspond to ONE applied Newton step (the loose 0.05 m /
        0.005 rad dsolve tolerances discard the second), so the final
        node positions are computed with the reference-pose T and the
        final T is rebuilt once at those positions — ``n_iter=1``, the
        default, replicates that (validated against the flexible
        analyzeCases golden; the high-frequency excitation-phase band is
        ~100x closer than the full fixed point).  ``n_iter>=2`` iterates
        toward the self-consistent fixed point
        T* = reduce(positions(T*, Xi0)) instead — the path-independent
        choice if matching the reference's solver-path artifact is not
        required.

        Returns (disp (n_nodes, 6), T (nFull, nDOF)).
        """
        Xi0 = np.asarray(Xi0, dtype=float)
        r0 = np.array([n.r0 for n in self.nodes])
        T_cur = np.asarray(T0)
        disp = None
        mutated = False
        try:
            for _ in range(max(1, int(n_iter))):
                disp = self.displacements(T_cur, reducedDOF, root_id, Xi0)
                if not np.any(disp):
                    break
                T_new, _, _ = self.reduce(positions=r0 + disp[:, :3])
                mutated = True
                dT = np.max(np.abs(T_new - T_cur))
                T_cur = T_new
                if dT <= atol:
                    break
        finally:
            if mutated:
                self.reduce()  # restore reference-pose traversal state
        return disp, T_cur

    def reduce_with_derivative(self):
        """T at the reference pose plus dT/d(reduced rotation dofs).

        Mirrors computeDerivativeTransformationMatrix
        (raft_fowt.py:640-667): perturb each rotational reduced DOF by a
        unit *linear* displacement (node shift = T-row), rebuild T from
        the shifted positions, subtract.  T is linear in node positions
        so this equals the analytic derivative.
        """
        T, reducedDOF, root_id = self.reduce()
        n_nodes = len(self.nodes)
        nDOF = len(reducedDOF)
        r0 = np.array([n.r0 for n in self.nodes])
        dT = np.zeros((6 * n_nodes, nDOF, nDOF))
        for i, dof in enumerate(reducedDOF):
            if dof[1] > 2 and self.nodes[dof[0]].end_node:
                disp = T[:, i].reshape(n_nodes, 6)[:, :3]
                Ti, _, _ = self.reduce(positions=r0 + disp)
                dT[:, :, i] = Ti - T
        # restore reference-pose traversal state
        self.reduce()
        return T, dT, reducedDOF, root_id
