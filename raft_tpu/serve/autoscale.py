"""SLO-driven autoscaler for the serving fleet (router-side daemon).

Scaling signals are the fleet's EXISTING telemetry — nothing new is
measured:

* **scale OUT** on sustained alert pressure: the router's alert
  engine (:func:`raft_tpu.obs.alerts.installed_engine`) firing
  ``slo-breach`` or ``breaker-storm`` means the fleet is missing its
  latency SLO or shedding replicas — more capacity, warmed from the
  shared AOT bank, costs zero compiles;
* **scale IN** on sustained low occupancy from the cost ledger: every
  replica's lease health snapshot carries ``busy_s`` (cumulative
  on-device wall across its banked programs — :func:`raft_tpu.aot.
  bank.ledger_summary`), so lease-to-lease deltas give a fleet
  busy-fraction without touching any replica.

The hysteresis/for-duration/cooldown state machine is NOT reinvented:
the two conditions are private :class:`~raft_tpu.obs.alerts.Rule`
entries (``autoscale-hot`` above 0.5 pressure for
``RAFT_TPU_AUTOSCALE_OUT_FOR_S``; ``autoscale-cold`` below
``RAFT_TPU_AUTOSCALE_LOW_OCC`` occupancy for the longer
``RAFT_TPU_AUTOSCALE_IN_FOR_S``) evaluated by a private
:class:`~raft_tpu.obs.alerts.AlertEngine` with an injectable clock —
exactly the engine the default pack runs on, so the for-duration and
resolve-hysteresis semantics are the drill-tested ones.  On top of
the rule durations: hard ``[AUTOSCALE_MIN, AUTOSCALE_MAX]`` bounds,
one action per tick, and ``AUTOSCALE_COOLDOWN_S`` between actions
(scale-out must not immediately un-scale on the next tick's stale
occupancy — the anti-flap guard the drill asserts).

Scale-out spawns a replica through :func:`raft_tpu.serve.fleet.
spawn_replica` (it joins via the normal lease path); scale-in POSTs
``/drain`` to the NEWEST joiner (LIFO — the operator's baseline
capacity is the last to go) and lets drain-equals-release remove it
from the ring.  Zero overhead when ``RAFT_TPU_AUTOSCALE_EVAL_S`` is
unset: no thread, no state.

1-core honesty: on this host replicas time-share one CPU, so scale-out
raises *availability* and queue fairness, not aggregate FLOP/s — the
drill asserts the control loop (signals, bounds, cooldown, no flap),
not a throughput win.  On a real pod each replica owns its slice and
the same loop buys real capacity.
"""

from __future__ import annotations

import threading
import time

from raft_tpu.obs import alerts, metrics
from raft_tpu.serve import fleet
from raft_tpu.utils import config
from raft_tpu.utils.structlog import log_event

#: alert rules whose sustained firing means "under-capacity"
PRESSURE_RULES = ("slo-breach", "breaker-storm")


def scaling_rules():
    """The two private for-duration rules the autoscaler evaluates
    (hysteresis both ways: a condition must HOLD to act and must stay
    clean to re-arm)."""
    out_for = float(config.get("AUTOSCALE_OUT_FOR_S"))
    in_for = float(config.get("AUTOSCALE_IN_FOR_S"))
    return [
        alerts.Rule("autoscale-hot", "gauge:autoscale_pressure:value",
                    "above", threshold=0.5, for_s=out_for,
                    clear_s=out_for, severity="info",
                    help="sustained slo-breach/breaker-storm pressure "
                         "— the fleet wants another replica"),
        alerts.Rule("autoscale-cold", "gauge:autoscale_occupancy:value",
                    "below",
                    threshold=float(config.get("AUTOSCALE_LOW_OCC")),
                    for_s=in_for, clear_s=in_for, severity="info",
                    help="sustained low cost-ledger occupancy — the "
                         "fleet is over-provisioned"),
    ]


class FleetBackend:
    """The autoscaler's side-effect seam against a real fleet: lease
    reads, alert pressure, replica spawn and drain.  Tests inject a
    fake with the same four observers + two actuators."""

    def __init__(self, root, designs_spec=(), clock=time.monotonic):
        self.root = root
        self.designs_spec = list(designs_spec)
        self.ledger = fleet.FleetLedger(root)
        self._clock = clock
        self._busy: dict = {}    # rid -> (busy_s, t) previous sample
        self._spawned = 0
        self._procs: list = []   # keep Popen handles (no zombie reap race)

    def n_replicas(self):
        return len(self.ledger.live())

    def occupancy(self):
        """Fleet busy-fraction in [0, 1]: mean per-replica rate of
        ``healthz.busy_s`` (the lease's cost-ledger wall) between
        consecutive samples.  0.0 until two samples exist — a cold
        autoscaler must not scale in on ignorance alone (the cold
        rule's for-duration covers the warm-up window)."""
        now = self._clock()
        live = self.ledger.live()
        fracs = []
        for rid, rec in live.items():
            busy = float((rec.get("healthz") or {}).get("busy_s") or 0.0)
            prev = self._busy.get(rid)
            self._busy[rid] = (busy, now)
            if prev is None or now <= prev[1]:
                continue
            frac = max(0.0, busy - prev[0]) / (now - prev[1])
            fracs.append(min(1.0, frac))
        self._busy = {rid: v for rid, v in self._busy.items()
                      if rid in live}
        return sum(fracs) / len(fracs) if fracs else 0.0

    def pressure(self):
        """1.0 while the process's installed alert engine has a
        :data:`PRESSURE_RULES` member actively firing, else 0.0 — the
        autoscaler rides the default pack's own for-duration/clear
        state, it does not re-derive SLO math."""
        engine = alerts.installed_engine()
        if engine is None:
            return 0.0
        names = {a.get("rule") for a in engine.active()}
        return 1.0 if names & set(PRESSURE_RULES) else 0.0

    def scale_out(self):
        """Spawn one replica into the fleet (normal lease join path);
        its replica id, or None when no designs spec was given (a
        design-less router can only scale in)."""
        if not self.designs_spec:
            return None
        self._spawned += 1
        proc, rid = fleet.spawn_replica(
            self.root, self.designs_spec,
            index=1000 + self._spawned)  # clear of operator indices:
        # the replica-fault forwarding (FLEET_FAULT_REPLICA) must never
        # target an autoscaler spawn
        self._procs.append(proc)
        return rid

    def scale_in(self):
        """Drain the NEWEST joiner (LIFO); drain-equals-release drops
        it from the ring, the failover ladder finishes its in-flight
        work.  Returns the drained replica id, or None."""
        from raft_tpu.serve.rollout import _http_drain

        live = self.ledger.live()
        if not live:
            return None
        rid = max(live, key=lambda r: float(live[r].get("claimed_t")
                                            or 0.0))
        rec = live[rid]
        if not _http_drain(rec.get("addr") or "127.0.0.1",
                           rec.get("port") or 0):
            return None
        return rid


class Autoscaler(threading.Thread):
    """Daemon thread ticking :meth:`step` every
    ``RAFT_TPU_AUTOSCALE_EVAL_S`` seconds.  All policy state (rule
    durations via a private :class:`~raft_tpu.obs.alerts.AlertEngine`,
    cooldown, bounds) lives here; all side effects live in the
    injectable ``backend``."""

    def __init__(self, root=None, designs_spec=(), backend=None,
                 clock=time.monotonic, interval_s=None, minimum=None,
                 maximum=None, cooldown_s=None):
        super().__init__(name="raft-autoscale", daemon=True)
        self.backend = backend if backend is not None \
            else FleetBackend(root, designs_spec, clock=clock)
        self._clock = clock
        self.interval_s = float(interval_s if interval_s is not None
                                else config.get("AUTOSCALE_EVAL_S"))
        self.minimum = int(minimum if minimum is not None
                           else config.get("AUTOSCALE_MIN"))
        self.maximum = int(maximum if maximum is not None
                           else config.get("AUTOSCALE_MAX"))
        self.cooldown_s = float(cooldown_s if cooldown_s is not None
                                else config.get("AUTOSCALE_COOLDOWN_S"))
        self.engine = alerts.AlertEngine(rules=scaling_rules(),
                                         sink_path=None, clock=clock)
        self._last_action_t = None
        self._stop_evt = threading.Event()

    def step(self, now=None):
        """One control tick.  Returns ``None`` or ``("out"|"in",
        replica_id)`` — at most one action per tick, bounded,
        cooldown-gated."""
        now = self._clock() if now is None else float(now)
        press = float(self.backend.pressure())
        occ = float(self.backend.occupancy())
        metrics.gauge("autoscale_pressure").set(press)
        metrics.gauge("autoscale_occupancy").set(occ)
        self.engine.evaluate({"gauge:autoscale_pressure:value": press,
                              "gauge:autoscale_occupancy:value": occ},
                             now=now)
        active = {a["rule"] for a in self.engine.active()}
        n = int(self.backend.n_replicas())
        cooling = (self._last_action_t is not None
                   and now - self._last_action_t < self.cooldown_s)
        if cooling:
            return None
        if "autoscale-hot" in active and n < self.maximum:
            rid = self.backend.scale_out()
            if rid is not None:
                self._last_action_t = now
                metrics.counter("autoscale_outs").inc()
                log_event("autoscale_out", replicas=n + 1,
                          reason="pressure", pressure=press)
                return "out", rid
        elif "autoscale-cold" in active and "autoscale-hot" not in active \
                and n > self.minimum:
            rid = self.backend.scale_in()
            if rid is not None:
                self._last_action_t = now
                metrics.counter("autoscale_ins").inc()
                log_event("autoscale_in", replica=rid, replicas=n - 1,
                          reason="low-occupancy",
                          occupancy=round(occ, 4))
                return "in", rid
        return None

    def run(self):
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.step()
            except Exception:
                pass  # a bad tick must never kill the router

    def stop(self):
        self._stop_evt.set()
        self.join(timeout=2.0)
