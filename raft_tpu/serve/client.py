"""Minimal stdlib client for the evaluation service.

Used by the bench load harness (``RAFT_TPU_BENCH_MODE=serve``) and the
subprocess tests; keep-alive ``http.client`` connections so hundreds of
synthetic clients stay cheap.  Not a public SDK — the wire format is
plain JSON over HTTP (see :mod:`raft_tpu.serve.http`).
"""

from __future__ import annotations

import http.client
import json


class ResponseDropped(RuntimeError):
    """The request was (or may have been) delivered but the connection
    died before its response arrived.  Deliberately NOT a
    ``ConnectionError``: callers gating on "no accepted response was
    dropped" (the bench SIGTERM-drain check) must see this as a drop,
    never as a clean connection refusal — and the client must never
    silently re-send a non-idempotent evaluate for it."""


class ServeClient:
    """One keep-alive connection to a service instance."""

    def __init__(self, host, port, client_id=None, timeout=300.0):
        self.host, self.port = host, int(port)
        self.client_id = client_id
        self.timeout = timeout
        self._conn = None
        #: response headers of the last completed round trip (the
        #: distributed-tracing tests read `traceparent` back from here)
        self.last_headers = {}

    def _connection(self):
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout)
        return self._conn

    def close(self):
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def request(self, method, path, payload=None, headers=None):
        """One round trip; returns ``(status_code, parsed_body)`` —
        JSON-decoded when possible, raw text otherwise (``/metrics``)."""
        body = None
        headers = dict(headers or {})
        if payload is not None:
            body = json.dumps(payload)
            headers["Content-Type"] = "application/json"
        if self.client_id:
            headers["X-Client"] = str(self.client_id)
        conn = self._connection()
        try:
            conn.request(method, path, body=body, headers=headers)
        except (http.client.HTTPException, ConnectionError, OSError):
            # SEND failed — the server never processed the request, so
            # one fresh-connection retry is safe even for POST (covers
            # the stale-keep-alive race)
            self.close()
            conn = self._connection()
            conn.request(method, path, body=body, headers=headers)
        try:
            resp = conn.getresponse()
            data = resp.read()
        except (http.client.HTTPException, ConnectionError, OSError) as e:
            # the request may have been ACCEPTED: re-sending would
            # duplicate a non-idempotent evaluation (and eat a second
            # quota token), and calling this a refusal would hide a
            # dropped response from the drain gate
            self.close()
            raise ResponseDropped(
                f"connection lost awaiting {method} {path}: {e!r}") from e
        self.last_headers = {k.lower(): v for k, v in resp.getheaders()}
        if resp.will_close:
            self.close()
        try:
            return resp.status, json.loads(data)
        except ValueError:
            return resp.status, data.decode(errors="replace")

    def evaluate(self, design, Hs, Tp, beta, out_keys=None,
                 escalate_f64=False, traceparent=None):
        payload = {"design": design, "Hs": Hs, "Tp": Tp, "beta": beta}
        if out_keys:
            payload["out_keys"] = list(out_keys)
        if escalate_f64:
            payload["escalate_f64"] = True
        headers = {"traceparent": traceparent} if traceparent else None
        return self.request("POST", "/evaluate", payload, headers=headers)

    def healthz(self):
        return self.request("GET", "/healthz")

    def metrics_text(self):
        return self.request("GET", "/metrics")
