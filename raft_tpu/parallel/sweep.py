"""Device-mesh sweep drivers: scale design/case evaluations over TPUs.

The reference sweeps designs with nested serial Python loops
(``/root/reference/raft/parametersweep.py:56-100``) and has no
distributed backend (SURVEY.md §2.1).  Here a sweep is one batched
tensor program laid out over a ``jax.sharding.Mesh``:

* the **batch** axis (designs x cases — embarrassingly parallel, each a
  ~6-DOF problem) shards over the ``dp`` mesh axis and rides ICI;
* the **frequency** axis — the workload's 'sequence' axis — can shard
  over ``sp``; the only cross-frequency couplings are the
  drag-linearisation RMS statistics and the convergence norm
  (raft_member.py:2084-2090), which XLA lowers to all-reduces when the
  sharded program is compiled (the moral equivalent of context
  parallelism for this physics).

Everything goes through GSPMD: we annotate in/out shardings and let the
compiler insert the collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices=None, axis_names=("dp",)):
    devices = np.array(jax.devices()[: n_devices or len(jax.devices())])
    if len(axis_names) == 1:
        shape = (len(devices),)
    else:
        # split devices as evenly as possible over two axes
        n = len(devices)
        dp = max(d for d in range(1, n + 1) if n % d == 0 and d * d <= n)
        shape = (n // dp, dp)
    return Mesh(devices.reshape(shape), axis_names)


def sweep_cases(evaluate, Hs, Tp, beta, mesh=None, out_keys=("PSD", "X0")):
    """Evaluate a batch of sea states, sharded over the mesh's dp axis.

    evaluate : scalar-case function from :func:`raft_tpu.api.make_case_evaluator`
    Hs/Tp/beta : (N,) arrays (N divisible by the dp axis size)
    """
    if mesh is None:
        mesh = make_mesh()
    batched = jax.vmap(lambda h, t, b: {k: evaluate(h, t, b)[k] for k in out_keys})
    sharding = NamedSharding(mesh, P("dp"))
    fn = jax.jit(batched, in_shardings=(sharding, sharding, sharding))
    args = [jax.device_put(jnp.asarray(x), sharding) for x in (Hs, Tp, beta)]
    return fn(*args)


def run_sweep_checkpointed(evaluate, Hs, Tp, beta, out_dir, shard_size=256,
                           mesh=None, out_keys=("PSD", "X0")):
    """Large design/case sweep with per-shard checkpointing and resume.

    The reference has no checkpoint/resume story for sweeps (SURVEY.md
    §5.4); here each shard of the batch is evaluated as one sharded
    program and written to ``<out_dir>/shard_NNNN.npz`` — re-running
    skips completed shards, so a pre-empted pod job resumes where it
    stopped.  Returns the dict of concatenated results.
    """
    import os

    os.makedirs(out_dir, exist_ok=True)
    Hs = np.asarray(Hs)
    Tp = np.asarray(Tp)
    beta = np.asarray(beta)
    n = len(Hs)
    n_shards = (n + shard_size - 1) // shard_size
    if mesh is None:
        mesh = make_mesh()
    ndev = mesh.devices.size

    results = []
    for s in range(n_shards):
        path = os.path.join(out_dir, f"shard_{s:04d}.npz")
        if os.path.exists(path):
            results.append(dict(np.load(path)))
            continue
        sl = slice(s * shard_size, min((s + 1) * shard_size, n))
        h, t, b = Hs[sl], Tp[sl], beta[sl]
        pad = (-len(h)) % ndev  # pad the tail shard to the device count
        if pad:
            h = np.concatenate([h, np.full(pad, h[-1])])
            t = np.concatenate([t, np.full(pad, t[-1])])
            b = np.concatenate([b, np.full(pad, b[-1])])
        out = sweep_cases(evaluate, h, t, b, mesh=mesh, out_keys=out_keys)
        out = {k2: np.asarray(v)[: sl.stop - sl.start] for k2, v in out.items()}
        np.savez(path, **out)
        results.append(out)

    return {k2: np.concatenate([r[k2] for r in results]) for k2 in out_keys}
