"""Traced (jax) twin of the build-time member-element computation.

The build-time path (:mod:`raft_tpu.structure.members`) reduces each
member's shell/ballast/cap geometry to per-element inertia constants
with numpy.  For the geometry design axis — the WEIS design variables
``member_d`` / ``member_t`` / ballast fills / mooring properties
(`/root/reference/raft/omdao_raft.py:26-343`,
`parametersweep.py:56-100`) — those constants must instead be traced
functions of the design parameters so ONE compiled evaluator serves an
entire geometry DoE (SURVEY §7.1 build-time/trace-time split).

This module re-derives the same element constants with ``jax.numpy``:

* the *shapes* (station count, strip count, element count, cap branch
  selection) are static — they depend only on the station layout;
* the *values* (diameters, thicknesses, fill lengths/densities) are
  traced inputs;
* the reference's equal-endpoint special cases in the frustum/box MoI
  formulas (helpers.py:65-146) are algebraic limits of the general
  polynomial forms, so single branch-free expressions reproduce them
  exactly (the ``(r2^5 - r1^5)/(r2 - r1)`` ratio is expanded to its
  polynomial to stay finite at equality).

Matches `/root/reference/raft/raft_member.py` getInertia :412-541 and
the cap/bulkhead block :659-823 through the same element layout as
``_build_inertia_elements`` / ``_cap_elements``.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


# --------------------------------------------------------- geometry bits

def vcv_circ(dA, dB, H):
    """Frustum volume + axial centroid (helpers.py:36-63, circular)."""
    A1 = jnp.pi / 4 * dA**2
    A2 = jnp.pi / 4 * dB**2
    Am = jnp.pi / 4 * dA * dB
    s = A1 + Am + A2
    V = s * H / 3.0
    hc = jnp.where(s != 0, (A1 + 2 * Am + 3 * A2) / jnp.where(s != 0, s, 1.0) * H / 4.0, 0.0)
    return V, hc


def vcv_rect(slA, slB, H):
    """Frustum volume + axial centroid (rectangular side pairs (2,))."""
    A1 = slA[0] * slA[1]
    A2 = slB[0] * slB[1]
    Am = jnp.sqrt(jnp.maximum(A1 * A2, 0.0))
    s = A1 + Am + A2
    V = s * H / 3.0
    hc = jnp.where(s != 0, (A1 + 2 * Am + 3 * A2) / jnp.where(s != 0, s, 1.0) * H / 4.0, 0.0)
    return V, hc


def moi_circ(dA, dB, H, rho):
    """Circular frustum radial/axial MoI about end A (helpers.py:65-83).

    The reference's dA==dB branch equals the limit of the general cone
    expression; ``(r2^5-r1^5)/(r2-r1)`` is expanded to the 4th-degree
    polynomial so one expression covers both."""
    r1 = dA / 2.0
    r2 = dB / 2.0
    # (r2^5 - r1^5)/(r2 - r1) = sum_{j=0..4} r2^j r1^(4-j)
    p4 = r2**4 + r2**3 * r1 + r2**2 * r1**2 + r2 * r1**3 + r1**4
    I_rad = (1 / 20) * rho * jnp.pi * H * p4 + (1 / 30) * rho * jnp.pi * H**3 * (
        r1**2 + 3 * r1 * r2 + 6 * r2**2)
    I_ax = (1 / 10) * rho * jnp.pi * H * p4
    zero = H == 0
    return jnp.where(zero, 0.0, I_rad), jnp.where(zero, 0.0, I_ax)


def moi_rect(La, Wa, Lb, Wb, H, rho):
    """Box frustum MoI about end A (helpers.py:85-146).  The general
    polynomial form; the reference's equal-side branches are exact
    specialisations of it (verified algebraically)."""
    x2 = (1 / 12) * rho * (
        (Lb - La) ** 3 * H * (Wb / 5 + Wa / 20)
        + (Lb - La) ** 2 * La * H * (3 * Wb / 4 + Wa / 4)
        + (Lb - La) * La**2 * H * (Wb + Wa / 2)
        + La**3 * H * (Wb / 2 + Wa / 2)
    )
    y2 = (1 / 12) * rho * (
        (Wb - Wa) ** 3 * H * (Lb / 5 + La / 20)
        + (Wb - Wa) ** 2 * Wa * H * (3 * Lb / 4 + La / 4)
        + (Wb - Wa) * Wa**2 * H * (Lb + La / 2)
        + Wa**3 * H * (Lb / 2 + La / 2)
    )
    z2 = rho * (Wb * Lb / 5 + Wa * Lb / 20 + La * Wb / 20 + Wa * La / 30) * H**3
    zero = H == 0
    Ixx = jnp.where(zero, 0.0, y2 + z2)
    Iyy = jnp.where(zero, 0.0, x2 + z2)
    Izz = jnp.where(zero, 0.0, x2 + y2)
    return Ixx, Iyy, Izz


def _interp1(x, xs, v):
    """Linear interp of traced values ``v`` over STATIC abscissae ``xs``
    at a STATIC query ``x`` — indices/weights resolve at trace time."""
    xs = np.asarray(xs, dtype=float)
    x = float(x)
    if x <= xs[0]:
        return v[0]
    if x >= xs[-1]:
        return v[-1]
    i = int(np.searchsorted(xs, x, side="right") - 1)
    f = (x - xs[i]) / (xs[i + 1] - xs[i])
    return v[i] * (1 - f) + v[i + 1] * f


def _sdiv(a, b):
    return jnp.where(b != 0, a / jnp.where(b != 0, b, 1.0), 0.0)


def traced_cap_elements(g, d, t):
    """jax twin of members._cap_elements: list of
    (mass, s_cg, Ixx, Iyy, Izz) with traced d (n,2) / t (n,).
    Branch selection is static (station/cap layout)."""
    out = []
    cap_L = g.cap_L
    if cap_L is None or len(cap_L) == 0:
        return out
    cap_t = g.cap_t_arr
    cap_d_in = g.cap_d_in_arr
    st = g.stations

    for ic in range(len(cap_L)):
        L = cap_L[ic]
        h = cap_t[ic]
        rho_cap = g.rho_shell
        if g.circular:
            d_hole = cap_d_in[ic]
            d_in = d[:, 0] - 2 * t
            if L == st[0]:
                dA = d_in[0]
                dB = _interp1(L + h, st, d_in)
                dAi = d_hole
                dBi = dB * _sdiv(dAi, dA)
            elif L == st[-1]:
                dA = _interp1(L - h, st, d_in)
                dB = d_in[-1]
                dBi = d_hole
                dAi = dA * _sdiv(dBi, dB)
            elif ic < len(cap_L) - 1 and L == cap_L[ic + 1]:
                dA = _interp1(L - h, st, d_in)
                dB = d_in[ic]
                dBi = d_hole
                dAi = dA * _sdiv(dBi, dB)
            elif ic > 0 and L == cap_L[ic - 1]:
                dA = d_in[ic]
                dB = _interp1(L + h, st, d_in)
                dAi = d_hole
                dBi = dB * _sdiv(dAi, dA)
            else:
                dA = _interp1(L - h / 2, st, d_in)
                dB = _interp1(L + h / 2, st, d_in)
                dM = _interp1(L, st, d_in)
                dMi = d_hole
                dAi = dA * _sdiv(dMi, dM)
                dBi = dB * _sdiv(dMi, dM)
            V_o, hco = vcv_circ(dA, dB, h)
            V_i, hci = vcv_circ(dAi, dBi, h)
            v_cap = V_o - V_i
            m_cap = v_cap * rho_cap
            hc_cap = _sdiv(hco * V_o - hci * V_i, V_o - V_i)
            Ir_o, Ia_o = moi_circ(dA, dB, h, rho_cap)
            Ir_i, Ia_i = moi_circ(dAi, dBi, h, rho_cap)
            I_rad = (Ir_o - Ir_i) - m_cap * hc_cap**2
            Ixx = Iyy = I_rad
            Izz = Ia_o - Ia_i
        else:
            sl_hole = jnp.asarray(cap_d_in[ic])
            sl_in = d - 2 * t[:, None]

            def interp2(x):
                return jnp.stack([_interp1(x, st, sl_in[:, 0]),
                                  _interp1(x, st, sl_in[:, 1])])

            if L == st[0]:
                slA = sl_in[0]
                slB = interp2(L + h)
                slAi = sl_hole
                slBi = slB * (slAi / slA)
            elif L == st[-1]:
                slB = sl_in[-1]
                slA = interp2(L - h)
                slBi = sl_hole
                slAi = slA * (slBi / slB)
            elif ic < len(cap_L) - 1 and L == cap_L[ic + 1]:
                slA = interp2(L - h)
                slB = sl_in[ic]
                slBi = sl_hole
                slAi = slA * (slBi / slB)
            elif ic > 0 and L == cap_L[ic - 1]:
                slA = sl_in[ic]
                slB = interp2(L + h)
                slAi = sl_hole
                slBi = slB * (slAi / slA)
            else:
                slA = interp2(L - h / 2)
                slB = interp2(L + h / 2)
                slM = interp2(L)
                slMi = sl_hole
                slAi = slA * (slMi / slM)
                slBi = slB * (slMi / slM)
            V_o, hco = vcv_rect(slA, slB, h)
            V_i, hci = vcv_rect(slAi, slBi, h)
            v_cap = V_o - V_i
            m_cap = v_cap * rho_cap
            hc_cap = _sdiv(hco * V_o - hci * V_i, V_o - V_i)
            Ix_o, Iy_o, Iz_o = moi_rect(slA[0], slA[1], slB[0], slB[1], h, rho_cap)
            Ix_i, Iy_i, Iz_i = moi_rect(slAi[0], slAi[1], slBi[0], slBi[1], h, rho_cap)
            Ixx = (Ix_o - Ix_i) - m_cap * hc_cap**2
            Iyy = (Iy_o - Iy_i) - m_cap * hc_cap**2
            Izz = Iz_o - Iz_i

        if L == st[0]:
            s_cg = L + hc_cap
        elif L == st[-1]:
            s_cg = L - (h - hc_cap)
        else:
            s_cg = L - (h / 2 - hc_cap)
        out.append((m_cap, s_cg, Ixx, Iyy, Izz))
    return out


def traced_inertia_elements(g, d, t, l_fill, rho_fill):
    """jax twin of members._build_inertia_elements for RIGID members.

    d : (n, 2) traced outer diameter/side pairs at stations
    t : (n,)  traced shell thickness
    l_fill : (n-1,) traced ballast fill lengths [m]
    rho_fill : (n-1,) traced ballast densities

    Returns (elem_mass, elem_s, elem_Ixx, elem_Iyy, elem_Izz) jnp arrays
    with exactly the static element layout of the build-time path
    (sections incl. the reference's zero-length-section quirk, then
    caps), plus (mshell, mfill (n-1,)).

    All sections are computed in one VECTORISED pass over the section
    axis; the zero-length-section quirk (re-adds the previous section's
    CG inertia with zero mass, members.py:597-614) is a static index
    map, so the element layout is one gather.  The per-section scalar
    formulation this replaces emitted thousands of scalar HLO ops per
    FOWT — a major contributor to evaluator compile time on the
    geometry axis.
    """
    st = np.asarray(g.stations, dtype=float)
    n = len(st)
    lsec_np = np.diff(st)                       # static section lengths
    pos = lsec_np > 0                           # static validity mask
    lsec = jnp.asarray(np.where(pos, lsec_np, 1.0))  # safe divisor
    posj = jnp.asarray(pos, dtype=float)
    lf = jnp.asarray(l_fill)
    rf = jnp.asarray(rho_fill)

    if g.circular:
        dA, dB = d[:-1, 0], d[1:, 0]
        dAi = dA - 2 * t[:-1]
        dBi = dB - 2 * t[1:]
        V_o, hco = vcv_circ(dA, dB, lsec)
        V_i, hci = vcv_circ(dAi, dBi, lsec)
        m_shell = (V_o - V_i) * g.rho_shell * posj
        hc_shell = _sdiv(hco * V_o - hci * V_i, V_o - V_i)
        dBi_fill = (dBi - dAi) * (lf / lsec) + dAi
        v_fill, hc_fill = vcv_circ(dAi, dBi_fill, lf)
        m_fill = v_fill * rf * posj
        mass = m_shell + m_fill
        hc = _sdiv(hc_fill * m_fill + hc_shell * m_shell, mass)
        Ir_o, Ia_o = moi_circ(dA, dB, lsec, g.rho_shell)
        Ir_i, Ia_i = moi_circ(dAi, dBi, lsec, g.rho_shell)
        Ir_f, Ia_f = moi_circ(dAi, dBi_fill, lf, rf)
        I_rad = ((Ir_o - Ir_i) * posj + Ir_f * posj) - mass * hc**2
        Ixx_s, Iyy_s = I_rad, I_rad
        Izz_s = ((Ia_o - Ia_i) + Ia_f) * posj
    else:
        slA, slB = d[:-1], d[1:]                # (n-1, 2)
        slAi = slA - 2 * t[:-1, None]
        slBi = slB - 2 * t[1:, None]
        V_o, hco = vcv_rect(slA.T, slB.T, lsec)
        V_i, hci = vcv_rect(slAi.T, slBi.T, lsec)
        m_shell = (V_o - V_i) * g.rho_shell * posj
        hc_shell = _sdiv(hco * V_o - hci * V_i, V_o - V_i)
        slBi_fill = (slBi - slAi) * (lf / lsec)[:, None] + slAi
        v_fill, hc_fill = vcv_rect(slAi.T, slBi_fill.T, lf)
        m_fill = v_fill * rf * posj
        mass = m_shell + m_fill
        hc = _sdiv(hc_fill * m_fill + hc_shell * m_shell, mass)
        Ix_o, Iy_o, Iz_o = moi_rect(slA[:, 0], slA[:, 1], slB[:, 0],
                                    slB[:, 1], lsec, g.rho_shell)
        Ix_i, Iy_i, Iz_i = moi_rect(slAi[:, 0], slAi[:, 1], slBi[:, 0],
                                    slBi[:, 1], lsec, g.rho_shell)
        Ix_f, Iy_f, Iz_f = moi_rect(slAi[:, 0], slAi[:, 1], slBi_fill[:, 0],
                                    slBi_fill[:, 1], lf, rf)
        Ixx_s = ((Ix_o - Ix_i) + Ix_f) * posj - mass * hc**2
        Iyy_s = ((Iy_o - Iy_i) + Iy_f) * posj - mass * hc**2
        Izz_s = ((Iz_o - Iz_i) + Iz_f) * posj

    s_sec = jnp.asarray(st[:-1]) + hc

    # static element layout: section index + mass/s mask per element;
    # zero-length sections reuse the PREVIOUS real section's inertia
    # with zero mass (and are skipped entirely before any real section)
    idx, msk = [], []
    prev = -1
    for j in range(n - 1):
        if pos[j]:
            idx.append(j)
            msk.append(1.0)
            prev = j
        elif prev >= 0:
            idx.append(prev)
            msk.append(0.0)
    idx = np.asarray(idx, dtype=int)
    msk_j = jnp.asarray(np.asarray(msk))
    elem_mass = mass[idx] * msk_j
    elem_s = s_sec[idx] * msk_j
    elem_Ixx = Ixx_s[idx]
    elem_Iyy = Iyy_s[idx]
    elem_Izz = Izz_s[idx]
    mshell = jnp.sum(m_shell)
    mfill = m_fill

    caps = traced_cap_elements(g, d, t)
    if caps:
        cm = jnp.stack([jnp.asarray(c[0], dtype=float) for c in caps])
        cs = jnp.stack([jnp.asarray(c[1], dtype=float) for c in caps])
        cx = jnp.stack([jnp.asarray(c[2], dtype=float) for c in caps])
        cy = jnp.stack([jnp.asarray(c[3], dtype=float) for c in caps])
        cz = jnp.stack([jnp.asarray(c[4], dtype=float) for c in caps])
        elem_mass = jnp.concatenate([elem_mass, cm])
        elem_s = jnp.concatenate([elem_s, cs])
        elem_Ixx = jnp.concatenate([elem_Ixx, cx])
        elem_Iyy = jnp.concatenate([elem_Iyy, cy])
        elem_Izz = jnp.concatenate([elem_Izz, cz])
        mshell = mshell + jnp.sum(cm)

    return (elem_mass, elem_s, elem_Ixx, elem_Iyy, elem_Izz, mshell, mfill)


# --------------------------------------------------------- FOWT assembly

def apply_geometry(fs, ss0, params, k=None):
    """Apply a traced geometry-parameter pytree to a FOWT.

    params keys (all optional; broadcastable scalars or (nMember,)):
      d_scale     outer diameter/side multiplier per member
      t_scale     shell thickness multiplier per member
      fill_scale  ballast fill-length multiplier per member
      rho_fill_scale  ballast density multiplier per member
      Cd_scale, Ca_scale  strip coefficient multipliers (global)

    Returns (fs2, ss2): a shallow FOWT copy whose rigid members carry
    traced d/t/elem_* (feeding the jax calc_statics/hydrostatics), and
    a StripSet with rescaled strip diameters.  Geometry tracing covers
    rigid members (the flagship workloads); flexible members keep their
    build-time FE constants.  MacCamy-Fuchs Cm factors are re-evaluated
    in-trace at the scaled kR through the canonical
    :func:`raft_tpu.physics.morison.mcf_cm` table (pass ``k`` (nw,)
    when the design has MCF members).
    """
    import copy
    import dataclasses

    nm = len(fs.members)
    one = jnp.ones(nm)
    d_s = jnp.broadcast_to(jnp.asarray(params.get("d_scale", 1.0)) * one, (nm,))
    t_s = jnp.broadcast_to(jnp.asarray(params.get("t_scale", 1.0)) * one, (nm,))
    f_s = jnp.broadcast_to(jnp.asarray(params.get("fill_scale", 1.0)) * one, (nm,))
    rf_s = jnp.broadcast_to(jnp.asarray(params.get("rho_fill_scale", 1.0)) * one, (nm,))

    members2 = []
    for im, mem in enumerate(fs.members):
        if mem.mtype != "rigid":
            members2.append(mem)
            continue
        d = jnp.asarray(mem.d) * d_s[im]
        t = jnp.asarray(mem.t) * t_s[im]
        lf = jnp.asarray(mem.l_fill) * f_s[im]
        rf = jnp.asarray(mem.rho_fill) * rf_s[im]
        em, es, ex, ey, ez, mshell, mfill = traced_inertia_elements(mem, d, t, lf, rf)
        members2.append(dataclasses.replace(
            mem, d=d, t=t, l_fill=lf, rho_fill=rf,
            ds=jnp.asarray(mem.ds) * d_s[im], drs=jnp.asarray(mem.drs) * d_s[im],
            elem_mass=em, elem_s=es, elem_Ixx=ex, elem_Iyy=ey, elem_Izz=ez,
            # traced shell/ballast bookkeeping so calc_statics
            # diagnostics (m_ballast, mshell totals) track the scaled
            # geometry instead of the build-time design
            mshell=mshell, mfill=mfill,
        ))
    fs2 = copy.copy(fs)
    fs2.members = members2

    # strip tensors: per-strip member scale (strip diameters are linear
    # in the station diameters for a fixed station layout)
    strip_mem = np.concatenate(
        [np.full(m.ns, i, dtype=int) for i, m in enumerate(fs.members)])
    sd = d_s[jnp.asarray(strip_mem)]
    Cd_s = jnp.asarray(params.get("Cd_scale", 1.0))
    Ca_s = jnp.asarray(params.get("Ca_scale", 1.0))
    ds2 = jnp.asarray(ss0.ds) * sd[:, None]
    Ca_p1_2 = jnp.asarray(ss0.Ca_p1) * Ca_s
    Ca_p2_2 = jnp.asarray(ss0.Ca_p2) * Ca_s
    # inertia coefficient tables: plain (1+Ca) strips scale with Ca; MCF
    # strips re-evaluate the wave-diffraction factor at the scaled kR
    Cm_p1_w = 1.0 + Ca_s * (jnp.asarray(ss0.Cm_p1_w) - 1.0)
    Cm_p2_w = 1.0 + Ca_s * (jnp.asarray(ss0.Cm_p2_w) - 1.0)
    mcf = np.asarray(ss0.mcf, dtype=bool)
    if mcf.any():
        from raft_tpu.physics.morison import mcf_blend

        if k is None:
            raise ValueError("apply_geometry needs k (nw,) for MCF members")
        kR = jnp.asarray(k)[None, :] * (ds2[:, 0] / 2.0)[:, None]
        Cm1_new, Cm2_new = mcf_blend(
            kR, (1.0 + Ca_p1_2)[:, None], (1.0 + Ca_p2_2)[:, None])
        sel = jnp.asarray(mcf)[:, None]
        Cm_p1_w = jnp.where(sel, Cm1_new, Cm_p1_w)
        Cm_p2_w = jnp.where(sel, Cm2_new, Cm_p2_w)
    ss2 = dataclasses.replace(
        ss0,
        ds=ds2,
        drs=jnp.asarray(ss0.drs) * sd[:, None],
        Cd_q=jnp.asarray(ss0.Cd_q) * Cd_s,
        Cd_p1=jnp.asarray(ss0.Cd_p1) * Cd_s,
        Cd_p2=jnp.asarray(ss0.Cd_p2) * Cd_s,
        Cd_End=jnp.asarray(ss0.Cd_End) * Cd_s,
        Ca_q=jnp.asarray(ss0.Ca_q) * Ca_s,
        Ca_p1=Ca_p1_2,
        Ca_p2=Ca_p2_2,
        Ca_End=jnp.asarray(ss0.Ca_End) * Ca_s,
        Cm_p1_w=Cm_p1_w,
        Cm_p2_w=Cm_p2_w,
    )
    return fs2, ss2
