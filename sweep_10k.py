"""North-star sweep demo (BASELINE.md): a 10k-design VolturnUS-S
geometry DoE — 100 w-bins x the 12-case operating table per design —
through the checkpointed sharded sweep
(``raft_tpu.parallel.sweep.run_sweep_checkpointed_full``).

This is the ``parametersweep.py:56-100`` workload done the TPU way: the
reference mutates the design dict and re-builds/re-runs the whole model
per variant (5 nested Python loops); here ONE compiled evaluator serves
every design — geometry (member d/t scale, ballast fill, mooring
length) enters the trace as parameters — and the design axis is sharded
over the device mesh, checkpointed per shard, and resumable.

Usage:
    python sweep_10k.py [--n 10000] [--shard 512] [--out _sweep10k]

Writes shard_NNNN.npz checkpoints plus SWEEP_10K.json with the
throughput summary.  Re-running resumes from completed shards.

Elastic fabric: ``RAFT_TPU_FABRIC_WORKERS=N python sweep_10k.py``
runs the SAME sweep N-way parallel with zero further changes — the
evaluator below carries a fabric entry spec
(:func:`fabric_entry`), so the checkpointed runner routes shards
through N worker subprocesses claiming leases from the shared ledger
(:mod:`raft_tpu.parallel.fabric`); results, shards and manifest are
bit-identical to the serial run.
"""

import argparse
import json
import os
import time

import numpy as np

from raft_tpu.utils import config


def build_design_evaluator():
    """Build the north-star per-design summary evaluator (12-case
    operating table folded to compact statistics) at module scope so
    both :func:`main` and the fabric workers' :func:`fabric_entry`
    construct the IDENTICAL traced program.  Returns
    ``(model, evaluate_design)``."""
    import jax
    import jax.numpy as jnp

    import bench

    model, evaluate = bench.build()       # geometry=True full evaluator
    dw = model.w[1] - model.w[0]
    case_cols = jnp.asarray(np.array(bench.CASES), dtype=jnp.float32)

    def evaluate_design(d):
        """One FULL design evaluation (12-case table) -> compact
        per-design summary statistics (keeps shard files small)."""
        g4 = d["g4"]
        gc = evaluate.geometry_constants(dict(
            d_scale=g4[0], t_scale=g4[1], fill_scale=g4[2],
            L_moor_scale=g4[3]))

        def one_case(c6):
            out = evaluate(dict(
                wind_speed=c6[0], wind_heading_deg=c6[1], TI=c6[2],
                Hs=c6[3], Tp=c6[4], beta_deg=c6[5], geom_const=gc))
            std = jnp.sqrt(jnp.sum(out["PSD"][:6] * dw, axis=-1))  # (6,)
            return dict(X0=out["X0"][:6], std=std,
                        drag_resid=out["drag_resid"],
                        status=out["status"])

        per_case = jax.vmap(one_case)(case_cols)   # (12, ...)
        x0 = per_case["X0"]
        std = per_case["std"]
        # per-design solver-health word: OR of the 12 cases' bits, so
        # the quarantine/escalation layer sees a flagged design even
        # when only one operating point misbehaved
        status = jax.lax.reduce(per_case["status"], np.int32(0),
                                jax.lax.bitwise_or, (0,))
        return dict(
            max_offset=jnp.max(jnp.hypot(x0[:, 0] + 3 * std[:, 0],
                                         x0[:, 1] + 3 * std[:, 1])),
            max_pitch_deg=jnp.rad2deg(
                jnp.max(jnp.abs(x0[:, 4]) + 3 * std[:, 4])),
            surge_std=std[:, 0], pitch_std=std[:, 4],
            X0=x0, drag_resid=jnp.max(per_case["drag_resid"]),
            status=status,
        )

    # AOT-bank identity for this wrapper closure: the inner evaluator's
    # design-content stamp plus the case table it bakes in — without
    # the stamp the sweep funnel memoizes but never banks the program
    # (raft_tpu.aot.bank), and resumed/fresh runs would re-trace
    from raft_tpu.aot import bank as aot_bank

    evaluate_design._raft_program_key = (
        "sweep10k_design_summary", aot_bank.program_key(evaluate),
        aot_bank.content_fingerprint(bench.CASES),
        # this wrapper's traced math lives OUTSIDE raft_tpu/ (the
        # bank's code fingerprint), so its source content joins the key
        aot_bank.file_fingerprint(os.path.abspath(__file__)))
    # fabric entry spec: lets RAFT_TPU_FABRIC_WORKERS=N route this
    # sweep through worker subprocesses that rebuild the evaluator via
    # fabric_entry below (raft_tpu.parallel.fabric)
    evaluate_design._raft_fabric_entry = {
        "entry": "sweep_10k:fabric_entry", "kwargs": {}}
    return model, evaluate_design


def fabric_entry(out_keys=("max_offset", "max_pitch_deg", "surge_std",
                           "pitch_std", "X0", "drag_resid", "status"),
                 shard_freq=False, **_):
    """Fabric worker entry: rebuild the design evaluator in the worker
    process and return its shard compute (the same
    :func:`raft_tpu.parallel.sweep.full_compute` path the serial
    checkpointed runner dispatches through)."""
    from raft_tpu.parallel.sweep import full_compute

    _, evaluate_design = build_design_evaluator()
    return full_compute(evaluate_design, out_keys=tuple(out_keys),
                        shard_freq=shard_freq)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=10000)
    ap.add_argument("--shard", type=int, default=512)
    ap.add_argument("--out", default="_sweep10k")
    ap.add_argument("--platform", default=config.get("BENCH_PLATFORM"))
    args = ap.parse_args()

    import jax

    # the shared funnel (raft_tpu.utils.devices.enable_compile_cache):
    # repo-local XLA disk cache (threshold from RAFT_TPU_CACHE_MIN_
    # COMPILE_S, default 0 so sub-10s CPU programs persist too), the
    # recompile-sentinel telemetry, and the AOT program-bank counters —
    # with RAFT_TPU_AOT=load a resumed/fresh run loads its sweep
    # programs from the bank instead of re-tracing for half a minute
    from raft_tpu.utils.devices import enable_compile_cache

    enable_compile_cache(
        cache_dir=os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "_jax_cache"),
        platform=args.platform or None)
    import bench
    from raft_tpu.parallel import resilience
    from raft_tpu.parallel.sweep import make_mesh, run_sweep_checkpointed_full

    # resolve the mesh BEFORE the first jax computation: the health
    # probe runs in a subprocess, and on a dead accelerator tunnel the
    # CPU-platform pin only takes effect if no in-process backend has
    # been initialized yet (bench.build() below is the first jnp touch)
    mesh = (None if args.platform else resilience.resolve_mesh(make_mesh))

    model, evaluate_design = build_design_evaluator()

    g4 = bench.sample_geometry(args.n, seed=11).astype(np.float32)
    if mesh is None:
        mesh = make_mesh()
    print(f"devices: {mesh.devices.size} x "
          f"{jax.devices()[0].device_kind}; {args.n} designs "
          f"(100w x {len(bench.CASES)} cases each)", flush=True)

    t0 = time.perf_counter()
    n_fresh = [0]

    def on_shard(done, total, fresh):
        """Incremental progress summary: a preempted run still leaves
        SWEEP_10K.json covering the completed shards."""
        n_fresh[0] += int(fresh)
        el = time.perf_counter() - t0
        rate = (n_fresh[0] * args.shard) / max(el, 1e-9)
        prog = dict(
            status="running" if done < total else "complete",
            shards_done=done, shards_total=total,
            designs_done=min(done * args.shard, args.n),
            wall_s=round(el, 2),
            design_evals_per_s_fresh=round(rate, 3),
            device_kind=jax.devices()[0].device_kind,
            n_devices=int(mesh.devices.size), out_dir=args.out)
        with open("SWEEP_10K.json", "w") as f:
            json.dump(prog, f, indent=1)
        print(f"shard {done}/{total} ({'fresh' if fresh else 'resumed'}), "
              f"{rate:.3f} evals/s", flush=True)

    out = run_sweep_checkpointed_full(
        evaluate_design, {"g4": g4}, args.out, shard_size=args.shard,
        mesh=mesh,
        out_keys=("max_offset", "max_pitch_deg", "surge_std", "pitch_std",
                  "X0", "drag_resid", "status"),
        on_shard=on_shard)
    wall = time.perf_counter() - t0

    n_done = len(out["max_offset"])
    # throughput counts only FRESHLY computed shards: a resumed re-run
    # loads shards from disk in seconds and must not overwrite the
    # artifact with a bogus thousands-of-evals/s headline
    fresh_designs = min(n_fresh[0] * args.shard, n_done)
    # reliability headline numbers come from the telemetry metrics
    # snapshot — the SAME counters the runtime increments and dumps to
    # <out_dir>/metrics.json at sweep_done — so this artifact and the
    # runtime's own accounting cannot drift (the previous ad-hoc
    # re-derivation from quarantine.json counted across ALL prior runs
    # while sweep_done counted this run only).  Quarantined designs are
    # excluded from the aggregates via nan-aware reductions — one
    # non-converged drag linearization must not poison the ranges.
    from raft_tpu.obs import metrics

    cnt = metrics.snapshot()["counters"]
    # quarantine.json keeps the cross-run audit list (resolved
    # escalation entries are audit records, not quarantined rows)
    quarantine_listed = [e for e in resilience.load_quarantine(args.out)
                         if not e.get("resolved")]
    # per-bit solver-health counts over the whole DoE (the in-band
    # status words persisted in the shards; see README "Solver health")
    from raft_tpu.utils import health

    status = np.asarray(out["status"])
    n_flagged = {name: int(((status & mask) != 0).sum())
                 for name, mask in health.MASKS.items()
                 if ((status & mask) != 0).any()}
    summary = dict(
        n_designs=int(n_done),
        n_quarantined=cnt.get("rows_quarantined", 0),
        n_quarantined_listed=len(quarantine_listed),
        n_flagged=n_flagged,
        n_flagged_severe=cnt.get("rows_flagged", 0),
        shard_retries=cnt.get("shard_retries", 0),
        shard_oom_splits=cnt.get("shard_oom_splits", 0),
        escalation_rungs=cnt.get("escalation_rungs", 0),
        escalations_resolved=cnt.get("escalations_resolved", 0),
        xla_compiles=cnt.get("xla_compiles", 0),
        xla_cache_hits=cnt.get("xla_cache_hits", 0),
        # cold-start provenance: which sweep programs came from the AOT
        # bank vs a fresh trace+compile this run (the same counters land
        # in <out_dir>/metrics.json and the manifest at sweep_done, so a
        # resumed run's artifact states its cache story instead of
        # implying a 33s trace that never happened)
        programs_loaded=cnt.get("aot_programs_loaded", 0),
        programs_compiled=cnt.get("aot_programs_compiled", 0),
        aot_mode=config.get("AOT"),
        cases_per_design=len(bench.CASES),
        n_freq=int(model.nw),
        wall_s=round(wall, 2),
        design_evals_per_s=(round(fresh_designs / wall, 3)
                            if fresh_designs else None),
        fresh_designs=int(fresh_designs),
        resumed_designs=int(n_done - fresh_designs),
        device_kind=jax.devices()[0].device_kind,
        n_devices=int(mesh.devices.size),
        shard_size=args.shard,
        out_dir=args.out,
        max_offset_range=[float(np.nanmin(out["max_offset"])),
                          float(np.nanmax(out["max_offset"]))],
        max_pitch_range=[float(np.nanmin(out["max_pitch_deg"])),
                         float(np.nanmax(out["max_pitch_deg"]))],
        worst_drag_resid=float(np.nanmax(out["drag_resid"])),
    )
    with open("SWEEP_10K.json", "w") as f:
        json.dump(summary, f, indent=1)
    # run-record twin of the artifact (RAFT_TPU_RUNS_DIR): the summary
    # scalars (design_evals_per_s above all) join the store so `obs
    # runs regress` can gate the north-star throughput trajectory
    from raft_tpu.obs import runs as obs_runs

    obs_runs.maybe_record("sweep_10k", label=args.out, wall_s=wall,
                          extra=summary)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
