"""Morison hydrodynamics parity vs reference golden pickles.

Mirrors /root/reference/tests/test_fowt.py: hydroConstants,
hydroExcitation (heading x period x height sweep), hydroLinearization
(prescribed response), and current loads, compared against the
reference's *_true_*.pkl at its own tolerances.
"""

import os
import pickle

import numpy as np
import pytest
from numpy.testing import assert_allclose

from tests.conftest import ref_data

import raft_tpu
from raft_tpu.models.hydro import FOWTHydro

DESIGNS = [
    "OC3spar.yaml",
    "VolturnUS-S.yaml",
    "VolturnUS-S-pointInertia.yaml",
    "OC4semi-WAMIT_Coefs.yaml",
]


def make_hydro(design_name):
    path = ref_data(design_name)
    if not os.path.exists(path):
        pytest.skip(f"missing reference data {path}")
    model = raft_tpu.Model(path)
    return path, FOWTHydro(model.fowtList[0], model.w, model.k)


@pytest.fixture(params=DESIGNS, ids=[d.split(".")[0] for d in DESIGNS])
def design_and_hydro(request):
    return make_hydro(request.param)


def test_hydro_constants(design_and_hydro):
    path, fh = design_and_hydro
    with open(path.replace(".yaml", "_true_hydroConstants.pkl"), "rb") as f:
        true = pickle.load(f)
    assert_allclose(
        np.asarray(fh.A_hydro_morison), true["A_hydro_morison"], rtol=1e-5, atol=1e-3
    )


def test_hydro_excitation(design_and_hydro):
    path, fh = design_and_hydro
    with open(path.replace(".yaml", "_true_hydroExcitation.pkl"), "rb") as f:
        true = pickle.load(f)
    idx = 0
    for wave_heading in [0, 45, 90, 135, 180, 225, 270, 315, 360]:
        for wave_period in [5, 10, 15, 20]:
            for wave_height in [1, 2]:
                case = {
                    "wave_heading": wave_heading,
                    "wave_period": wave_period,
                    "wave_height": wave_height,
                }
                out = fh.hydro_excitation(case)
                assert_allclose(
                    np.asarray(out["F_hydro_iner"]),
                    true[idx]["F_hydro_iner"],
                    rtol=1e-5, atol=1e-3,
                    err_msg=f"case {case}",
                )
                idx += 1


def test_hydro_linearization(design_and_hydro):
    path, fh = design_and_hydro
    with open(path.replace(".yaml", "_true_hydroLinearization.pkl"), "rb") as f:
        true = pickle.load(f)
    case = {"wave_spectrum": "unit", "wave_heading": 0,
            "wave_period": 10, "wave_height": 2}
    fh.hydro_excitation(case)
    nDOF, nw = fh.fs.nDOF, fh.nw
    phase = np.linspace(0, 2 * np.pi, nw * nDOF).reshape(nDOF, nw)
    Xi = 0.1 * np.exp(1j * phase)
    out = fh.hydro_linearization(Xi, ih=0)
    assert_allclose(
        np.asarray(out["B_hydro_drag"]), true["B_hydro_drag"], rtol=1e-5, atol=1e-10
    )
    assert_allclose(
        np.asarray(out["F_hydro_drag"]), true["F_hydro_drag"], rtol=1e-5
    )


def test_current_loads(design_and_hydro):
    path, fh = design_and_hydro
    with open(path.replace(".yaml", "_true_calcCurrentLoads.pkl"), "rb") as f:
        true = pickle.load(f)
    D = fh.current_loads({"current_speed": 2.0, "current_heading": 15})
    assert_allclose(np.asarray(D), true, rtol=1e-5, atol=1e-3)
