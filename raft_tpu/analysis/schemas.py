"""Writer/reader schema contracts for cross-process record families.

The coordinator, the fabric workers, the serve process and every CLI
agree on the shape of the JSON records they exchange — lease files,
done records, worker status files, ``fabric.json``, ``manifest.json``,
``quarantine.json``, run records, AOT bank sidecars — only by
convention.  Nothing enforces that a key a reader dereferences is ever
written, or that a key a reader *requires* (hard ``rec["k"]``
subscript) is written unconditionally; drift between a writer and a
reader in two different processes is silent data loss or a crash in a
process the author never ran.

This engine extracts, statically, the **written key set** and the
**read key set** of each record family from its declared write/read
sites (:data:`FAMILIES`) and fails on drift:

* ``read-never-written`` — a reader dereferences a key no writer ever
  emits (the classic typo: writer says ``renewed_t``, reader asks for
  ``renewd_t`` — both sides "work" until a steal decision reads a
  garbage default);
* ``required-but-conditional`` — a reader hard-subscripts
  (``rec["k"]``, KeyError on absence) a key that writers only emit
  conditionally (inside an ``if``, or only at some call sites of a
  kwargs-style writer);
* ``baseline-drift`` — the extracted contract differs from the
  checked-in ``analysis/schema_baseline.json``: intentional schema
  evolution must be an explicit, reviewed diff (regenerate with
  ``python -m raft_tpu.analysis schemas --write``), never an accident.

Extraction handles the repo's actual idioms: dict literals (on the
record variable, returned, or passed inline to an atomic writer),
``rec["k"] = v`` / ``rec.setdefault`` / ``rec.update(...)`` mutations
(conditional when nested under ``if``/``for``/``while``/``except``;
``try``/``with`` bodies count as unconditional), kwargs-style writers
(key set = the union over call sites; a key missing from any call site
is conditional), and reads via ``rec["k"]`` (required), ``rec.get``
/ ``setdefault`` / ``in`` (optional) — including loops and
comprehensions over literal key tuples and over module-level constant
tuples (``for k in _STRICT_FINGERPRINT_KEYS: old.get(k)``).

Pure stdlib ``ast`` — no jax import.  Run
``python -m raft_tpu.analysis schemas``.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass

from raft_tpu.analysis.lint import repo_root

BASELINE_NAME = "schema_baseline.json"


@dataclass(frozen=True)
class Site:
    """One write or read site of a record family.

    path : repo-relative module path
    func : function qualname ("Ledger.claim", "init_sweep")
    var : the name holding the record inside ``func`` — a local, a
        parameter, or a ``self.<attr>`` spelling.  ``None`` on writer
        sites means "every dict literal returned from, or passed
        inline to an atomic-writer call inside, this function".
    kind : writer sites only — ``create`` (authoritative full record:
        family alwaysness intersects over these), ``update``
        (read-modify-write that preserves unknown keys: only adds its
        keys), ``kwargs`` (the function collects ``**var``; the written
        keys are the union over its call sites in the family's files)
    """

    path: str
    func: str
    var: str | None = None
    kind: str = "create"


@dataclass(frozen=True)
class Family:
    """One cross-process record family: its writers and readers."""

    name: str
    help: str
    writers: tuple = ()
    readers: tuple = ()
    #: extra files scanned for call sites of kwargs-style writers
    callers: tuple = ()


# ------------------------------------------------------------ the contract

_FAB = "raft_tpu/parallel/fabric.py"
_RES = "raft_tpu/parallel/resilience.py"
_RUNS = "raft_tpu/obs/runs.py"
_OBS_CLI = "raft_tpu/obs/__main__.py"
_BANK = "raft_tpu/aot/bank.py"
_FLEET = "raft_tpu/serve/fleet.py"
_ROUTER = "raft_tpu/serve/router.py"
_ALERTS = "raft_tpu/obs/alerts.py"
_CANARY = "raft_tpu/serve/canary.py"
_RELEASE = "raft_tpu/aot/release.py"
_ROLLOUT = "raft_tpu/serve/rollout.py"
_FLIGHT = "raft_tpu/obs/flight.py"

FAMILIES: tuple[Family, ...] = (
    Family(
        "lease", "shard lease file (fabric ledger claim/renew/steal)",
        writers=(Site(_FAB, "Ledger.claim", "rec"),
                 Site(_FAB, "Ledger.renew", "rec", kind="update")),
        readers=(Site(_FAB, "Ledger.renew", "rec"),
                 Site(_FAB, "Ledger.release", "rec"),
                 Site(_FAB, "Ledger.stealable", "rec"),
                 Site(_FAB, "Ledger.summary", "rec"),
                 Site(_FAB, "Worker._try_adopt", "rec"),
                 Site(_FAB, "Worker._lease_attempt", "rec"))),
    Family(
        "done-record", "shard completion record (fabric ledger)",
        writers=(Site(_FAB, "Ledger.write_done", "rec", kind="kwargs"),),
        callers=(_FAB,),
        readers=(Site(_FAB, "assemble", "rec"),
                 Site(_FAB, "run_fabric.report_progress", "rec"))),
    Family(
        "worker-status", "fabric worker status file (liveness + pooling)",
        writers=(Site(_FAB, "Ledger.write_worker_status", "rec",
                      kind="kwargs"),),
        callers=(_FAB,),
        readers=(Site(_FAB, "Ledger.pooled_walls", "st"),
                 Site(_FAB, "Ledger.summary", "st"),
                 Site(_FAB, "assemble", "st"))),
    Family(
        "fabric-spec", "fabric.json sweep spec (coordinator -> workers)",
        writers=(Site(_FAB, "init_sweep", "spec"),),
        readers=(Site(_FAB, "Worker.run", "spec"),
                 Site(_FAB, "Worker._setup_runtime", "spec"),
                 Site(_FAB, "Worker._eval_shard", "self.spec"),
                 Site(_FAB, "assemble", "spec"),
                 Site(_FAB, "main", "spec"))),
    Family(
        "manifest", "manifest.json top level (resume validation)",
        writers=(Site(_RES, "init_manifest", "manifest"),
                 Site(_FAB, "assemble", "manifest", kind="update"),),
        readers=(Site(_RES, "init_manifest", "manifest"),
                 Site(_FAB, "assemble", "manifest"))),
    Family(
        "fingerprint", "manifest config fingerprint (strict + advisory)",
        writers=(Site(_RES, "compute_fingerprint", None),),
        readers=(Site(_RES, "init_manifest", "old"),
                 Site(_RES, "validate_manifest", "old"))),
    Family(
        "quarantine-entry", "quarantine.json schema-v2 row entries",
        writers=(Site(_RES, "_quarantine_shard", "entry"),),
        readers=(Site(_RES, "record_quarantine", "e"),
                 Site(_RES, "run_checkpointed", "e"),
                 Site(_FAB, "Worker._eval_shard", "e"))),
    Family(
        "run-record", "schema-v1 longitudinal run record (obs.runs)",
        writers=(Site(_RUNS, "build_record", "record"),
                 Site(_RUNS, "ingest_bench", None)),
        readers=(Site(_RUNS, "load_record", "record"),
                 Site(_RUNS, "flatten", "record"),
                 Site(_RUNS, "env_mismatch", "a"),
                 Site(_RUNS, "env_mismatch", "b"),
                 Site(_RUNS, "regress_records", "new"),
                 Site(_RUNS, "regress_records", "base"),
                 Site(_OBS_CLI, "_cmd_runs_list", "rec"))),
    Family(
        "fleet-lease",
        "serving-fleet replica membership lease (_fleet/replicas/; "
        "claim = join, renewed = alive, expired = dead, release = "
        "drain — raft_tpu.serve.fleet)",
        writers=(Site(_FLEET, "FleetLedger.claim", "rec"),
                 Site(_FLEET, "FleetLedger.renew", "rec", kind="update")),
        readers=(Site(_FLEET, "FleetLedger.renew", "rec"),
                 Site(_FLEET, "FleetLedger.release", "rec"),
                 Site(_FLEET, "FleetLedger.lease_age", "rec"),
                 Site(_FLEET, "FleetLedger.live", "rec"),
                 Site(_FLEET, "FleetLedger.expired", "rec"),
                 Site(_FLEET, "FleetLedger.summary", "rec"),
                 Site(_ROUTER, "RouterState.apply_membership", "rec"),
                 Site(_ROUTER, "LedgerProber.probe_once", "rec"))),
    Family(
        "router-membership",
        "the router's published membership view (_fleet/router.json: "
        "ring replicas + breaker states, advisory)",
        writers=(Site(_ROUTER, "RouterState.membership_record", "rec"),),
        readers=(Site(_FLEET, "FleetLedger.summary", "router"),)),
    Family(
        "alert-record",
        "alert fire/resolve transition record (the RAFT_TPU_ALERTS "
        "JSONL sink + the alert_fire/alert_resolve event payload — "
        "raft_tpu.obs.alerts)",
        writers=(Site(_ALERTS, "AlertEngine._record", None),),
        readers=(Site(_ALERTS, "read_sink", "rec"),
                 Site(_ALERTS, "render_sink_summary", "rec"))),
    Family(
        "canary-golden",
        "content-addressed golden row of the serving canary (design "
        "content hash + exact case bits + out_keys -> outputs + int32 "
        "status — raft_tpu.serve.canary)",
        writers=(Site(_CANARY, "CanaryState.capture", "rec"),),
        readers=(Site(_CANARY, "CanaryState.compare", "golden"),
                 Site(_CANARY, "CanaryState.observe", "golden"))),
    Family(
        "aot-sidecar", "AOT bank entry .json metadata sidecar",
        writers=(Site(_BANK, "entry_key", "meta"),
                 Site(_BANK, "store", "meta", kind="update")),
        readers=(Site(_BANK, "lookup", "meta"),
                 Site(_BANK, "is_stale", "meta"),
                 Site(_BANK, "verify_bank", "meta"),
                 Site(_BANK, "gc_bank", "meta"))),
    Family(
        "release-manifest",
        "signed, content-addressed release manifest (releases/<id>."
        "json: bank entry shas + code/flags/ladder identity + parent "
        "chain + captured env — raft_tpu.aot.release)",
        writers=(Site(_RELEASE, "build_manifest", "man"),
                 Site(_RELEASE, "sign_manifest", "man", kind="update")),
        readers=(Site(_RELEASE, "verify_manifest", "man"),
                 Site(_RELEASE, "verify_against_bank", "man"),
                 Site(_RELEASE, "classify_mismatch", "man"),
                 Site(_RELEASE, "walk_parents", "man"),
                 Site(_RELEASE, "list_releases", "man"),
                 Site(_RELEASE, "parity_context", "man"))),
    Family(
        "rollout-record",
        "rolling-upgrade outcome record (the run record's extra block "
        "+ the rollout CLI/drill summary — raft_tpu.serve.rollout)",
        writers=(Site(_ROLLOUT, "build_record", "record"),),
        readers=(Site(_ROLLOUT, "summarize_record", "record"),)),
    Family(
        "flight-dump",
        "flight-recorder dump shard header (flight-*.jsonl first line: "
        "a proc_start clock anchor carrying the schema-versioned "
        "flight metadata block — raft_tpu.obs.flight; the shard body "
        "reuses the live structlog event layout)",
        writers=(Site(_FLIGHT, "_header_record", "rec"),),
        readers=(Site(_FLIGHT, "read_shard", "hdr"),
                 Site(_FLIGHT, "show", "hdr"))),
)


# ============================================================== extraction


def _load_tree(root, path, _cache={}):
    key = os.path.join(root, path)
    if key not in _cache:
        with open(key, encoding="utf-8") as f:
            src = f.read()
        _cache[key] = (ast.parse(src, filename=key), src)
    return _cache[key]


def _find_func(tree, qualname):
    """The (Async)FunctionDef for a dotted qualname; supports one
    nesting level per dot (class method, nested closure)."""
    node = tree
    for part in qualname.split("."):
        nxt = None
        for child in ast.walk(node):
            if child is node:
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)) and child.name == part:
                nxt = child
                break
        if nxt is None:
            raise LookupError(f"no function {qualname!r}")
        node = nxt
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise LookupError(f"{qualname!r} is not a function")
    return node


def _module_const_tuples(tree):
    """Module-level NAME = ("a", "b", ...) string-tuple constants, for
    resolving ``for k in _STRICT_FINGERPRINT_KEYS:`` style reads."""
    out = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            keys = _str_tuple(node.value)
            if keys is not None:
                out[node.targets[0].id] = keys
    return out


def _str_tuple(node):
    """The tuple of string constants a node denotes, or None."""
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


def _matches_var(node, var):
    """Does ``node`` denote the record variable ``var`` (a bare name,
    ``self.attr``, or a defaulted spelling like ``(rec or {})``)?"""
    if isinstance(node, ast.BoolOp):  # (rec or {})
        return any(_matches_var(v, var) for v in node.values)
    if "." in var:
        base, attr = var.split(".", 1)
        return (isinstance(node, ast.Attribute) and node.attr == attr
                and isinstance(node.value, ast.Name)
                and node.value.id == base)
    return isinstance(node, ast.Name) and node.id == var


class _SiteWalker:
    """Shared conditional-context walker: visits every node of one
    function with an ``conditional`` flag that is True under ``if``/
    ``for``/``while``/``except``/ternary (``try`` and ``with`` bodies
    count as unconditional — they run unless the process dies, which
    for schema purposes is 'always')."""

    def __init__(self, func_node, consts):
        self.func = func_node
        self.consts = consts  # module constant str-tuples
        #: loop-variable name -> tuple of keys it ranges over
        self.loop_keys = {}

    def _iter_keys(self, it):
        keys = _str_tuple(it)
        if keys is None and isinstance(it, ast.Name):
            keys = self.consts.get(it.id)
        return keys

    def walk(self):
        yield from self._walk(self.func, False)

    def _register(self, node):
        """Bind literal-key loop variables BEFORE their bodies are
        visited: ``for k in ("a", "b"):`` and ``{k: rec.get(k) for k
        in (...)}`` are unrolled key sequences, not dynamic access."""
        if isinstance(node, ast.For):
            keys = self._iter_keys(node.iter)
            if keys is not None and isinstance(node.target, ast.Name):
                self.loop_keys[node.target.id] = keys
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                keys = self._iter_keys(gen.iter)
                if keys is not None and isinstance(gen.target, ast.Name):
                    self.loop_keys[gen.target.id] = keys

    def _branch_cond(self, node, fieldname, cond):
        """The conditionality of one child field: an ``if``'s TEST is
        evaluated unconditionally, its body/orelse are not; a loop over
        literal keys runs for every key (unconditional), any other loop
        body may run zero times."""
        if isinstance(node, (ast.If, ast.IfExp, ast.While)) \
                and fieldname in ("body", "orelse"):
            return True
        if isinstance(node, ast.For) and fieldname in ("body", "orelse"):
            literal = (self._iter_keys(node.iter) is not None
                       and isinstance(node.target, ast.Name))
            return cond if literal else True
        if isinstance(node, ast.Try) \
                and fieldname in ("handlers", "orelse"):
            return True
        return cond

    def _walk(self, node, cond):
        self._register(node)
        for fieldname, value in ast.iter_fields(node):
            bc = self._branch_cond(node, fieldname, cond)
            for child in (value if isinstance(value, list) else [value]):
                if not isinstance(child, ast.AST) or isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # nested defs are their own sites
                yield child, bc
                yield from self._walk(child, bc)

    def key_of(self, node):
        """Keys a subscript/get argument denotes: a literal string, or
        a loop variable bound to a literal tuple."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return (node.value,)
        if isinstance(node, ast.Name) and node.id in self.loop_keys:
            return self.loop_keys[node.id]
        return None


def _extract_writes(root, site, call_keys=None):
    """{key: "always" | "conditional"} written by one writer site."""
    tree, _ = _load_tree(root, site.path)
    func = _find_func(tree, site.func)
    consts = _module_const_tuples(tree)
    w = _SiteWalker(func, consts)
    out = {}

    def note(key, cond):
        if key is None:
            return
        for k in (key if isinstance(key, tuple) else (key,)):
            if out.get(k) != "always":
                out[k] = "conditional" if cond else "always"

    def note_dict(node, cond):
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                note(k.value, cond)

    for node, cond in w.walk():
        if site.var is None:
            # returned dict literals + dict literals passed to an
            # atomic-writer call
            if isinstance(node, ast.Return) and isinstance(node.value,
                                                           ast.Dict):
                note_dict(node.value, cond)
            if isinstance(node, ast.Call):
                fname = (node.func.attr if isinstance(node.func,
                                                      ast.Attribute)
                         else getattr(node.func, "id", None))
                if fname in ("_atomic_json", "dump"):
                    for a in node.args:
                        if isinstance(a, ast.Dict):
                            note_dict(a, cond)
            continue
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if _matches_var(t, site.var) and isinstance(node.value,
                                                            ast.Dict):
                    note_dict(node.value, cond)
                elif isinstance(t, ast.Subscript) \
                        and _matches_var(t.value, site.var):
                    note(w.key_of(t.slice), cond)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        if isinstance(e, ast.Subscript) \
                                and _matches_var(e.value, site.var):
                            note(w.key_of(e.slice), cond)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and _matches_var(node.func.value, site.var):
            if node.func.attr == "setdefault" and node.args:
                note(w.key_of(node.args[0]), cond)
            elif node.func.attr == "update":
                for kw in node.keywords:
                    if kw.arg is not None:
                        note(kw.arg, cond)
                for a in node.args:
                    if isinstance(a, ast.Dict):
                        note_dict(a, cond)
    if site.kind == "kwargs" and call_keys is not None:
        # call-site keywords: present at EVERY call site -> always
        # (within this writer), else conditional
        sites_seen = call_keys
        if sites_seen:
            every = set.intersection(*[set(s) for s in sites_seen])
            union = set.union(*[set(s) for s in sites_seen])
            for k in union:
                status = "always" if k in every else "conditional"
                if out.get(k) != "always":
                    out[k] = status
    return out


def _kwarg_call_sites(root, family, writer):
    """Keyword-name sets of every call to a kwargs-style writer within
    the family's caller files (positional-only calls contribute an
    empty set — they write no keys)."""
    fname = writer.func.split(".")[-1]
    sites = []
    for path in (family.callers or (writer.path,)):
        tree, _ = _load_tree(root, path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            called = (f.attr if isinstance(f, ast.Attribute)
                      else getattr(f, "id", None))
            if called != fname:
                continue
            sites.append({kw.arg for kw in node.keywords
                          if kw.arg is not None})
    return sites


def _extract_reads(root, site):
    """{key: "required" | "optional"} read by one reader site.

    A hard subscript is ``required`` — unless every such subscript of
    the key sits in a conditional branch AND the same function also
    ``.get``-reads it: that is the presence-guard idiom (``if
    rec.get(k) is not None: use rec[k]``), which tolerates absence."""
    tree, _ = _load_tree(root, site.path)
    func = _find_func(tree, site.func)
    consts = _module_const_tuples(tree)
    w = _SiteWalker(func, consts)
    required = {}   # key -> True when any subscript is unconditional
    optional = set()
    writes = set()  # keys this function itself assigns on the var:
    #                 a read-back of one's own write is not a contract

    for node, cond in w.walk():
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and _matches_var(t.value, site.var):
                    for k in (w.key_of(t.slice) or ()):
                        writes.add(k)
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and _matches_var(node.value, site.var):
            for k in (w.key_of(node.slice) or ()):
                required[k] = required.get(k, False) or not cond
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("get", "setdefault") \
                and _matches_var(node.func.value, site.var) and node.args:
            optional.update(w.key_of(node.args[0]) or ())
        elif isinstance(node, ast.Compare) \
                and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and _matches_var(node.comparators[0], site.var):
            optional.update(w.key_of(node.left) or ())
    out = {}
    for k, unconditional in required.items():
        guarded = not unconditional and k in optional
        out[k] = "optional" if guarded else "required"
    for k in optional:
        out.setdefault(k, "optional")
    for k in writes:
        out.pop(k, None)
    return out


def extract_family(family, root=None):
    """``{"written": {key: always|conditional}, "read": {key:
    required|optional}}`` for one family, merged across its sites.

    Merge rules: a key is ``always`` only when every *create* writer
    always writes it (``update``/``kwargs`` writers add keys without
    demoting other writers' alwaysness — they rewrite or extend an
    existing record); reads keep the strictest classification
    (``required`` wins)."""
    root = root or repo_root()
    create_sets, update_keys = [], set()
    for writer in family.writers:
        call_keys = (_kwarg_call_sites(root, family, writer)
                     if writer.kind == "kwargs" else None)
        keys = _extract_writes(root, writer, call_keys=call_keys)
        if writer.kind in ("create", "kwargs"):
            # a kwargs writer is create-ish: every record of the family
            # passes through it (call-site intersection already decided
            # per-key alwaysness inside _extract_writes)
            create_sets.append(keys)
        else:
            # update writers rewrite an EXISTING record: they can add
            # keys, but a record that never met them lacks those keys,
            # so update-only keys are at best conditional
            update_keys.update(keys)
    written = {}
    if create_sets:
        union = set().union(*[set(s) for s in create_sets])
        for k in sorted(union):
            statuses = [s.get(k) for s in create_sets]
            written[k] = ("always" if all(st == "always" for st in statuses)
                          else "conditional")
    for k in update_keys:
        written.setdefault(k, "conditional")
    read = {}
    for reader in family.readers:
        for k, v in _extract_reads(root, reader).items():
            if v == "required" or k not in read:
                read[k] = v
    return {"written": dict(sorted(written.items())),
            "read": dict(sorted(read.items()))}


def extract_all(families=FAMILIES, root=None):
    return {f.name: extract_family(f, root=root) for f in families}


# ================================================================ checking


def drift_violations(name, contract):
    """Writer/reader drift within one freshly-extracted contract."""
    out = []
    written, read = contract["written"], contract["read"]
    for key, how in sorted(read.items()):
        if key not in written:
            out.append(
                f"[{name}] read-never-written: readers dereference "
                f"{key!r} but no writer site ever emits it")
        elif how == "required" and written[key] == "conditional":
            out.append(
                f"[{name}] required-but-conditional: a reader hard-"
                f"subscripts {key!r} (KeyError on absence) but writers "
                "only emit it conditionally")
    return out


def baseline_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        BASELINE_NAME)


def load_baseline(path=None):
    with open(path or baseline_path(), encoding="utf-8") as f:
        return json.load(f)


def write_baseline(contracts, path=None):
    path = path or baseline_path()
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "families": contracts}, f, indent=1,
                  sort_keys=True)
        f.write("\n")
    return path


def baseline_violations(contracts, baseline):
    """Differences between the extracted contracts and the checked-in
    baseline — intentional evolution must be an explicit regen."""
    out = []
    base = baseline.get("families", {})
    for name in sorted(set(contracts) | set(base)):
        got, want = contracts.get(name), base.get(name)
        if want is None:
            out.append(f"[{name}] not in the baseline (new family?) — "
                       "regen with `schemas --write`")
            continue
        if got is None:
            out.append(f"[{name}] in the baseline but no longer "
                       "extracted — regen with `schemas --write`")
            continue
        for side, label in (("written", "writer"), ("read", "reader")):
            g, b = got.get(side, {}), want.get(side, {})
            for k in sorted(set(g) | set(b)):
                if g.get(k) != b.get(k):
                    out.append(
                        f"[{name}] {label} key {k!r}: extracted "
                        f"{g.get(k)!r}, baseline {b.get(k)!r} — schema "
                        "evolution must be an explicit `schemas --write` "
                        "diff")
    return out


def run_checks(families=FAMILIES, root=None, baseline=None,
               check_baseline=True):
    """Full engine pass: ``(violations, contracts)``."""
    contracts = extract_all(families, root=root)
    violations = []
    for name, contract in contracts.items():
        violations.extend(drift_violations(name, contract))
    if check_baseline:
        try:
            base = baseline if baseline is not None else load_baseline()
        except (OSError, ValueError) as e:
            violations.append(f"[baseline] unreadable {baseline_path()}: "
                              f"{e} — regen with `schemas --write`")
        else:
            violations.extend(baseline_violations(contracts, base))
    return violations, contracts


# ================================================================= fixture

#: the seeded drift drill: a deliberately drifted lease writer/reader
#: pair (tests/fixtures/lint/bad_schema_writer.py) that the engine must
#: catch — the CI negative `lint.sh` asserts exits EXACTLY 1
FIXTURE_PATH = os.path.join("tests", "fixtures", "lint",
                            "bad_schema_writer.py")

FIXTURE_FAMILY = Family(
    "drifted-lease", "seeded drift drill (bad_schema_writer.py fixture)",
    writers=(Site(FIXTURE_PATH, "write_lease", "rec"),),
    readers=(Site(FIXTURE_PATH, "read_lease", "rec"),))


def run_fixture_checks(root=None):
    """Violations of the seeded drift fixture (baseline not consulted:
    the fixture is a negative, not part of the repo contract)."""
    return run_checks((FIXTURE_FAMILY,), root=root, check_baseline=False)
