"""Hierarchical telemetry spans over the structured-log stream.

A span is a timed region of host-side work — a driver run, one statics
solve, one sweep shard, one retry attempt, one escalation rung.  Spans
emit paired ``span_begin``/``span_end`` JSONL events carrying
``trace_id`` (shared by a whole nested tree), ``span_id`` and the
parent's id, propagated through a :mod:`contextvars` variable so
nesting works across function boundaries (and stays correctly scoped
per thread/async task).  Every other ``log_event`` fired inside a span
automatically carries the enclosing trace/span ids, which is what lets
``python -m raft_tpu.obs report`` attribute a ``shard_retry`` to the
shard (and sweep) it happened in.

Overhead discipline: with ``RAFT_TPU_LOG`` unset, a span is a sink
check, a clock read and one histogram observe (a few microseconds) —
no ids are generated, no contextvar is touched, nothing is emitted;
the ``span_<name>_s`` wall-time histograms stay on either way, so a
Prometheus scrape (``RAFT_TPU_METRICS``) carries per-stage timings
even when the event stream is off.  All instrumentation is host-side
only: spans never run under a jax trace, so the jaxpr contract suite
sees zero new primitives.

Device-trace alignment: when ``RAFT_TPU_PROFILE`` is set, each span
also enters a ``jax.profiler.TraceAnnotation`` of the same name, so
the host span shows up on the profiler timeline next to the XLA device
slices it caused (the ``named_scope`` annotations inside the sweep's
traced programs carry the same names down onto device ops).
"""

from __future__ import annotations

import re
import time
import uuid

from raft_tpu.obs import flight, metrics
from raft_tpu.utils import config, structlog


def _new_id():
    return uuid.uuid4().hex[:16]


def current_ids():
    """(trace_id, span_id) of the innermost active span, or None."""
    return structlog.SPAN_CTX.get()


# ------------------------------------------------- cross-process propagation

#: W3C trace-context `traceparent`: version "00", 32-hex trace id,
#: 16-hex parent span id, 2-hex flags.  Lenient on trace-id length
#: (internal ids are 16 hex; foreign tracers send 32).
_TRACEPARENT_RE = re.compile(
    r"^\s*([0-9a-f]{2})-([0-9a-f]{16,32})-([0-9a-f]{16})-([0-9a-f]{2})\s*$")


def parse_traceparent(header):
    """``(trace_id, parent_span_id)`` from one ``traceparent`` header
    (or the ``RAFT_TPU_TRACEPARENT`` env value), else None.  The trace
    id keeps whatever meaningful hex the sender used (leading zero
    padding from :func:`format_traceparent` is stripped back off so a
    round trip is identity for internal 16-hex ids)."""
    if not header or not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.lower())
    if not m:
        return None
    trace_id, span_id = m.group(2), m.group(3)
    if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
        return None  # all-zero ids are "no trace" per the W3C spec
    stripped = trace_id.lstrip("0")
    if len(trace_id) == 32 and len(stripped) <= 16:
        trace_id = stripped.rjust(16, "0")
    return trace_id, span_id


def format_traceparent(trace_id=None, span_id=None):
    """The ``traceparent`` header/env value for (trace_id, span_id) —
    default: the innermost active span of this task/thread.  None when
    there is no active span (nothing to propagate)."""
    if trace_id is None or span_id is None:
        ctx = structlog.SPAN_CTX.get()
        if ctx is None:
            return None
        trace_id, span_id = ctx
    return f"00-{str(trace_id).rjust(32, '0')}-{str(span_id).rjust(16, '0')}-01"


def remote_context():
    """The trace context inherited from a parent process
    (``RAFT_TPU_TRACEPARENT``), parsed, or None.  A process's first
    root span joins this trace instead of minting a fresh trace_id —
    which is what stitches fabric workers (and anything else spawned
    with :func:`propagation_env`) into the coordinator's timeline."""
    return parse_traceparent(config.raw("TRACEPARENT"))


def ambient_ids():
    """(trace_id, span_id-or-parent) for stamping cross-process
    records (fabric lease/done files): the active span's ids when
    inside one, else the inherited remote context, else None."""
    ctx = structlog.SPAN_CTX.get()
    if ctx is not None:
        return ctx
    return remote_context()


def propagation_env():
    """Env vars that stitch a child process into this one's telemetry:
    always the run id (a worker minting its own uuid is exactly the
    split-timeline bug this exists to prevent), plus the traceparent
    when called inside an active span."""
    env = {config.env_name("RUN_ID"): structlog.run_id()}
    tp = format_traceparent()
    if tp is None:
        # no active span (e.g. logging off in the parent): still
        # forward any context *we* inherited, so a grandchild chains
        tp = config.raw("TRACEPARENT") or None
    if tp:
        env[config.env_name("TRACEPARENT")] = tp
    return env


class span:
    """Context manager for one telemetry span::

        with obs.span("shard", shard=3, rows=256):
            ...

    Emits ``span_begin``/``span_end`` (the latter with ``wall_s``,
    ``ok`` and a truncated ``error`` on failure) and observes the wall
    time into the ``span_<name>_s`` histogram of the metrics registry.
    Exceptions always propagate."""

    __slots__ = ("name", "attrs", "trace_id", "span_id",
                 "_token", "_t0", "_ann", "_remote")

    def __init__(self, name, remote=None, **attrs):
        """``remote=(trace_id, parent_span_id)`` adopts an explicit
        cross-process parent (e.g. a parsed HTTP ``traceparent``) for a
        ROOT span; a nested span always keeps its in-process parent.
        With no explicit remote, a root span consults
        ``RAFT_TPU_TRACEPARENT`` (:func:`remote_context`)."""
        self.name = name
        self.attrs = attrs
        self.trace_id = None
        self.span_id = None
        self._token = None
        self._t0 = None
        self._ann = None
        self._remote = remote

    def __enter__(self):
        if config.raw("PROFILE"):
            # mirror the span onto the jax profiler timeline; must not
            # be able to break the instrumented computation
            try:
                from jax.profiler import TraceAnnotation

                self._ann = TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter()
        if not structlog.enabled():
            # fast path: no ids, no contextvar, no event — the flight
            # ring still records the begin (ids are synthesized at
            # dump time from the per-thread nesting order)
            flight.capture_span_begin(self.name, self.attrs)
            return self
        parent = structlog.SPAN_CTX.get()
        kw = {}
        if parent is None:
            # root span: adopt a cross-process parent — an explicit one
            # (HTTP traceparent) first, else the env-inherited context a
            # coordinator pinned into this process (fabric workers) —
            # so the whole fleet shares ONE trace instead of N
            remote = self._remote or remote_context()
            if remote is not None:
                parent = remote
                kw["remote_parent"] = True
        self.trace_id = parent[0] if parent else _new_id()
        self.span_id = _new_id()
        self._token = structlog.SPAN_CTX.set((self.trace_id, self.span_id))
        structlog.log_event(
            "span_begin", name=self.name,
            parent_id=parent[1] if parent else None, **kw, **self.attrs)
        return self

    def __exit__(self, exc_type, exc, tb):
        wall = time.perf_counter() - self._t0
        # the wall-time histogram feeds unconditionally (metrics exist
        # without the event stream); events only when the sink is live.
        # With live ids the observation carries an exemplar, so a
        # /metrics scrape can name the actual slowest span instance.
        if self.span_id is not None:
            metrics.histogram(f"span_{self.name}_s").observe(
                wall, exemplar={"trace_id": self.trace_id,
                                "span_id": self.span_id})
        else:
            metrics.histogram(f"span_{self.name}_s").observe(wall)
            flight.capture_span_end(self.name, wall, exc_type is None)
        if self._token is not None:
            kw = {}
            if exc_type is not None:
                kw["error"] = repr(exc)[:200]
            structlog.log_event(
                "span_end", name=self.name, wall_s=round(wall, 6),
                ok=exc_type is None, **kw)
            structlog.SPAN_CTX.reset(self._token)
            self._token = None
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
            self._ann = None
        return False
